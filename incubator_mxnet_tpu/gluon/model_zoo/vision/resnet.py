"""ResNet V1/V2 (reference gluon/model_zoo/vision/resnet.py).

The flagship benchmark model (BASELINE config 2: ResNet-50).  Identical
architecture to the reference zoo: V1 = post-activation (He et al. 2015),
V2 = pre-activation (He et al. 2016), thumbnail variant for CIFAR.

TPU extensions (reference-compatible additions, not divergences):
- ``layout="NHWC"``: channel-minor data layout end to end (the
  reference's Conv2D layout knob, its cuDNN fp16 fast path; here the
  layout the Pallas fused-block kernels read).
- ``fused=True`` (+ NHWC): bottleneck training forwards run the fused
  Pallas path (ops/fused_block.py + ops/fused_conv.py) — convs emit BN
  batch stats from their epilogues and apply the previous BN's
  normalize+ReLU in their prologues, eliminating the BN-structured HBM
  traffic the round-4 roofline identified.  BottleneckV1 (resnet
  50/101/152 v1) and the pre-activation BottleneckV2 (v2 family, whose
  bn->relu->conv ordering maps directly onto the prologue) are both
  covered; stride-2 v2 3x3s keep an XLA conv (the kernel is s1-only).
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock, register_state_update
from ....ops.registry import invoke

__all__ = ["ResNetV1", "ResNetV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _bn_axis(layout):
    return -1 if layout == "NHWC" else 1


def _check_fused(fused, layout, cls):
    """fused=True must never silently degrade to the plain path: a
    benchmark tagged 'fusedblk' (bench.py metric suffix) has to mean the
    fused kernels actually ran."""
    if not fused:
        return
    if cls not in ("BottleneckV1", "BottleneckV2"):
        raise ValueError(
            f"fused=True is implemented for the bottleneck blocks only "
            f"(ResNet-50/101/152 v1 and v2); {cls} has no fused path")
    if layout != "NHWC":
        raise ValueError(
            "fused=True requires layout='NHWC' (the fused matmul+BN "
            "kernels read channel-minor [M, C] views)")


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, layout=layout)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        _check_fused(fused, layout, type(self).__name__)
        ax = _bn_axis(layout)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.relu(x_out + residual)


def _bn_args(bn):
    return (bn.gamma.data(), bn.beta.data(),
            bn.running_mean.data(), bn.running_var.data())


def _bns_uniform(bns):
    """The fused registry ops take ONE eps/momentum and always use
    batch stats; a BN mutated after construction (use_global_stats, or
    a differing eps/momentum) must route the block through the layer
    path instead of being silently mis-normalized (ADVICE r4)."""
    ref = bns[0]
    return all(not getattr(bn, "_use_global_stats", False)
               and bn._epsilon == ref._epsilon
               and bn._momentum == ref._momentum for bn in bns)


def _invoke_fused_bottleneck(x, op, pairs, extra_args, state_bns, stride):
    """Assemble (x, [w_i, bn_i params]..., extra) for a fused-bottleneck
    registry op, invoke it, and route the returned moving stats through
    register_state_update (the BatchNorm contract).  Shared by the V1
    and V2 blocks so the arg marshaling cannot drift."""
    from ....ops import fused_block  # noqa: F401 — registers the ops
    args = [x]
    for conv, bn in pairs:
        args.append(conv.weight.data())
        args.extend(_bn_args(bn))
    args.extend(extra_args)
    outs = invoke(op, *args, stride=stride, eps=pairs[0][1]._epsilon,
                  momentum=pairs[0][1]._momentum)
    for i, bn in enumerate(state_bns):
        register_state_update(bn.running_mean, outs[1 + 2 * i])
        register_state_update(bn.running_var, outs[2 + 2 * i])
    return outs[0]


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        _check_fused(fused, layout, "BottleneckV1")
        ax = _bn_axis(layout)
        self._stride = stride
        self._fused = bool(fused)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                use_bias=False, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                use_bias=False, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def _finish_deferred(self, x):
        """Resolve deferred parameter shapes without running the body
        (the fused path bypasses the child layers' forwards)."""
        ci = x.shape[-1]
        cm = self.body[0]._channels
        co = self.body[6]._channels
        for conv, cin in ((self.body[0], ci), (self.body[3], cm),
                          (self.body[6], cm)):
            if conv.weight._data is None:
                conv.weight.shape = ((conv._channels,) + conv._kernel
                                     + (cin // conv._groups,))
                conv.weight._finish_deferred_init()
        for bn, c in ((self.body[1], cm), (self.body[4], cm),
                      (self.body[7], co)):
            for p in (bn.gamma, bn.beta, bn.running_mean, bn.running_var):
                if p._data is None:
                    p.shape = (c,)
                    p._finish_deferred_init()
        if self.downsample is not None:
            dconv, dbn = self.downsample[0], self.downsample[1]
            if dconv.weight._data is None:
                dconv.weight.shape = ((dconv._channels,) + dconv._kernel
                                      + (ci // dconv._groups,))
                dconv.weight._finish_deferred_init()
            for p in (dbn.gamma, dbn.beta, dbn.running_mean,
                      dbn.running_var):
                if p._data is None:
                    p.shape = (co,)
                    p._finish_deferred_init()

    def _forward_fused(self, x):
        self._finish_deferred(x)
        bn1, bn2, bn3 = self.body[1], self.body[4], self.body[7]
        pairs = ((self.body[0], bn1), (self.body[3], bn2),
                 (self.body[6], bn3))
        if self.downsample is not None:
            dconv, dbn = self.downsample[0], self.downsample[1]
            return _invoke_fused_bottleneck(
                x, "_fused_bottleneck_v1_proj", pairs,
                (dconv.weight.data(),) + _bn_args(dbn),
                (bn1, bn2, bn3, dbn), self._stride)
        return _invoke_fused_bottleneck(
            x, "_fused_bottleneck_v1", pairs, (), (bn1, bn2, bn3),
            self._stride)

    def _fused_bns_uniform(self):
        bns = [self.body[1], self.body[4], self.body[7]]
        if self.downsample is not None:
            bns.append(self.downsample[1])
        return _bns_uniform(bns)

    def forward(self, x):
        if self._fused:
            from .... import autograd
            if autograd.is_training() and self._fused_bns_uniform():
                return self._forward_fused(x)
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.relu(x_out + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        _check_fused(fused, layout, "BasicBlockV2")
        ax = _bn_axis(layout)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        _check_fused(fused, layout, "BottleneckV2")
        ax = _bn_axis(layout)
        self._stride = stride
        self._fused = bool(fused)
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False, layout=layout)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def _finish_deferred(self, x):
        """Resolve deferred parameter shapes without running the child
        layers (the fused path bypasses their forwards)."""
        ci = x.shape[-1]
        cm = self.conv1._channels
        co = self.conv3._channels
        for conv, cin in ((self.conv1, ci), (self.conv2, cm),
                          (self.conv3, cm)):
            if conv.weight._data is None:
                conv.weight.shape = ((conv._channels,) + conv._kernel
                                     + (cin // conv._groups,))
                conv.weight._finish_deferred_init()
        # pre-activation: bn1 spans the block INPUT channels
        for bn, c in ((self.bn1, ci), (self.bn2, cm), (self.bn3, cm)):
            for p in (bn.gamma, bn.beta, bn.running_mean, bn.running_var):
                if p._data is None:
                    p.shape = (c,)
                    p._finish_deferred_init()
        if self.downsample is not None and \
                self.downsample.weight._data is None:
            d = self.downsample
            d.weight.shape = ((d._channels,) + d._kernel
                              + (ci // d._groups,))
            d.weight._finish_deferred_init()

    def _fused_bns_uniform(self):
        return _bns_uniform((self.bn1, self.bn2, self.bn3))

    def _forward_fused(self, x):
        self._finish_deferred(x)
        pairs = ((self.conv1, self.bn1), (self.conv2, self.bn2),
                 (self.conv3, self.bn3))
        state_bns = (self.bn1, self.bn2, self.bn3)  # v2: no shortcut BN
        if self.downsample is not None:
            return _invoke_fused_bottleneck(
                x, "_fused_bottleneck_v2_proj", pairs,
                (self.downsample.weight.data(),), state_bns,
                self._stride)
        return _invoke_fused_bottleneck(
            x, "_fused_bottleneck_v2", pairs, (), state_bns,
            self._stride)

    def forward(self, x):
        if self._fused:
            from .... import autograd
            if autograd.is_training() and self._fused_bns_uniform():
                return self._forward_fused(x)
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        x = self.relu(self.bn3(x))
        x = self.conv3(x)
        return x + residual


class S2DStem(HybridBlock):
    """Space-to-depth ResNet stem (the MLPerf TPU trick): s2d(2) then a
    4x4/s1 conv over 12 channels replaces the 7x7/s2 conv over 3.

    Same function class and FLOPs as the classic stem (the 7x7 kernel
    embeds exactly into the s2d domain — equivalence verified to 1.2e-6
    by scripts/perf_probe.py stem), but the contraction reads 12*16=192
    taps instead of 3*49=147 over a C=3 input that packs the 128-lane
    MXU at 2.3% density — the top conv-lowering lever identified in
    docs/performance.md.  Select with resnet50_v1(stem="s2d") or
    BENCH_STEM=s2d.
    """

    def __init__(self, channels, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        if layout != "NCHW":
            raise ValueError("stem='s2d' is NCHW-only (space_to_depth op "
                             "layout); use the conv7 stem with NHWC")
        self.conv = nn.Conv2D(channels, 4, 1, 2, use_bias=False,
                              in_channels=12)

    def forward(self, x):
        from .... import nd
        if x.shape[-1] % 2 or x.shape[-2] % 2:
            raise ValueError(
                f"stem='s2d' needs even spatial dims (got "
                f"{x.shape[-2:]}); use the default conv7 stem for odd "
                "crop sizes")
        y = nd.space_to_depth(x, block_size=2)
        y = self.conv(y)
        # pad 2 yields 113x113 for the canonical (2,1) asymmetric pad;
        # drop the last row/col (receptive-field shift the trained
        # weights absorb)
        return y[:, :, :-1, :-1]


def _add_stem(features, channels, thumbnail, stem, layout="NCHW"):
    if thumbnail:
        features.add(_conv3x3(channels, 1, 0, layout))
        return
    if stem == "s2d":
        features.add(S2DStem(channels, layout=layout))
    else:
        features.add(nn.Conv2D(channels, 7, 2, 3, use_bias=False,
                               layout=layout))
    features.add(nn.BatchNorm(axis=_bn_axis(layout)))
    features.add(nn.Activation("relu"))
    features.add(nn.MaxPool2D(3, 2, 1, layout=layout))


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv7", layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self._layout = layout
        self.features = nn.HybridSequential()
        _add_stem(self.features, channels[0], thumbnail, stem, layout)
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i], layout=layout, fused=fused))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes)

    def _make_layer(self, block, layers, channels, stride, in_channels=0,
                    layout="NCHW", fused=False):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout, fused=fused))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout, fused=fused))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv7", layout="NCHW", fused=False, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(axis=_bn_axis(layout), scale=False,
                                       center=False))
        _add_stem(self.features, channels[0], thumbnail, stem, layout)
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels, layout=layout, fused=fused))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm(axis=_bn_axis(layout)))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    _make_layer = ResNetV1._make_layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, **kwargs):
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise RuntimeError("no pretrained weights in zero-egress environment")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
