"""DataLoader with background prefetch (reference gluon/data/dataloader.py).

The reference forks worker *processes* and ships NDArrays through shared
memory (dataloader.py:28-133, cpu_shared_storage_manager.h).  On TPU the
device does the heavy math and batches flow host→HBM, so the re-design
uses a *thread* pool (no pickling; JAX arrays are process-local) plus
async double-buffering: the next batch is assembled and ``device_put``
while the current step runs — the prefetcher role of the reference's
``PrefetcherIter`` (src/io/iter_prefetcher.h).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = onp.asarray(data)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    return nd.array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch):
                    futures.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                batch = futures.pop(0).result()
                try:
                    futures.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass
                yield batch

    def __len__(self):
        return len(self._batch_sampler)
