"""DataLoader with background prefetch (reference gluon/data/dataloader.py).

The reference forks worker *processes* and ships NDArrays through shared
memory (dataloader.py:28-133, cpu_shared_storage_manager.h).  Both
strategies exist here:

* ``thread_pool=True`` (default): a thread pool with async
  double-buffering — no pickling, JAX arrays stay process-local; right
  whenever decode/augment releases the GIL (numpy, the native
  RecordIO iterator) — the prefetcher role of the reference's
  ``PrefetcherIter`` (src/io/iter_prefetcher.h).
* ``thread_pool=False`` with ``num_workers>0``: forked worker
  PROCESSES assembling batches into POSIX shared memory
  (``multiprocessing.shared_memory``), the TPU-native analog of the
  reference's shared-mem NDArray pickling + cpu_shared_storage_manager
  — right for GIL-bound Python augmentation.  Workers are numpy-only
  (they never touch JAX, so forking under an initialized backend is
  safe); the parent maps each segment zero-copy and uploads straight
  to the device.
"""
from __future__ import annotations

import contextlib as _contextlib
import traceback
from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ... import ndarray as nd
from ... import trace
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


# ---------------------------------------------------------------------------
# device-side prefetch ring (whole-loop compilation, fuse_loop.py)
# ---------------------------------------------------------------------------

def _block_to_device(arrs):
    """Stack per-step batches into one (K, batch, ...) device block.

    One host-side stack + one async ``jax.device_put`` is the fast
    path (the transfer the ring overlaps with the previous chunk's
    compute).  CPU-backend jax arrays take it too — ``onp.asarray``
    on host-resident buffers is near-zero-copy, and K per-array jnp
    dispatches cost more than the whole chunk saves (measured 0.67 ms
    vs 0.13 ms for a 16-step block).  Only accelerator-resident
    inputs stack device-side: downloading them to restack on host
    would force the sync this class exists to avoid.
    """
    import jax

    vals = [a.data if isinstance(a, NDArray) else a for a in arrs]
    if not all(isinstance(v, onp.ndarray) for v in vals):
        on_host = all(
            (not hasattr(v, "devices"))
            or all(d.platform == "cpu" for d in v.devices())
            for v in vals)
        if not on_host:
            import jax.numpy as jnp
            return jnp.stack(vals, axis=0)
        vals = [onp.asarray(v) for v in vals]
    return jax.device_put(onp.stack(vals, axis=0))


class DevicePrefetchRing:
    """Group a loader's per-step ``(x, y)`` batches into K-step device
    blocks, keeping ``depth`` blocks' host→device transfers in flight
    ahead of the consumer (double-buffered by default).

    ``jax.device_put``/``jnp.stack`` dispatch asynchronously, so
    building block *t+1* while the chunked train loop computes block
    *t* overlaps the copy with compute — the scanned program never
    waits on the host.  The existing host-side prefetcher threads
    (``DataLoader(num_workers=...)``) feed this ring unchanged: it
    consumes whatever batch iterator it is given.

    Yields ``("chunk", xs, ys)`` for full K-step blocks and one final
    ``("tail", [(x, y), ...])`` when the epoch length is not divisible
    by K — the consumer runs tail steps through the per-step path
    rather than compiling a second, shorter loop program.
    """

    def __init__(self, batches, chunk_steps, depth=2):
        from ...base import resolve_chunk_steps
        self.chunk_steps = resolve_chunk_steps(chunk_steps)
        self.depth = int(depth)
        if self.depth < 1:
            raise ValueError(f"depth must be >= 1, got {self.depth}")
        self._it = iter(batches)
        self.blocks = 0
        self.tail_steps = 0

    def _next_block(self):
        # fill span: draw K batches from the loader + launch the
        # host→device upload (async device_put) — the producer half of
        # the overlap the ring exists for (no-op without a trace)
        with trace.span("prefetch.fill", steps=self.chunk_steps,
                        block=self.blocks):
            pairs = []
            for _ in range(self.chunk_steps):
                try:
                    pairs.append(next(self._it))
                except StopIteration:
                    break
            if not pairs:
                return None
            if len(pairs) < self.chunk_steps:
                self.tail_steps = len(pairs)
                return ("tail", pairs)
            xs = _block_to_device([x for x, _ in pairs])
            ys = _block_to_device([y for _, y in pairs])
            self.blocks += 1
            return ("chunk", xs, ys)

    def __iter__(self):
        from collections import deque
        q = deque()
        exhausted = False
        while True:
            # drain span only when the consumer actually has to WAIT
            # for a fill (ring empty): nonzero drain time here is the
            # "dataloader can't keep up" signal a chunk timeline shows
            starved = not q and not exhausted
            with (trace.span("prefetch.drain") if starved
                  else _contextlib.nullcontext()):
                while not exhausted and len(q) < self.depth:
                    block = self._next_block()
                    if block is None:
                        exhausted = True
                        break
                    q.append(block)
                    if block[0] == "tail":
                        exhausted = True
            if not q:
                return
            yield q.popleft()


def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(s)) for s in zip(*data))
    arr = onp.asarray(data)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    return nd.array(arr)


# ---------------------------------------------------------------------------
# multiprocess workers: numpy-only children + shared-memory transport
# ---------------------------------------------------------------------------

def _np_batchify(data):
    """Worker-side batchify: stack into NUMPY (children never touch JAX)."""
    if isinstance(data[0], tuple):
        return tuple(_np_batchify([s[i] for s in data])
                     for i in range(len(data[0])))
    first = data[0]
    if isinstance(first, NDArray):
        raise TypeError(
            "multiprocess DataLoader workers are numpy-only (JAX arrays "
            "are process-local); return numpy from the dataset/transform "
            "or use thread_pool=True")
    arr = onp.stack([onp.asarray(d) for d in data], axis=0)
    if arr.dtype == onp.float64:
        arr = arr.astype(onp.float32)
    return arr


def _tree_to_shm(tree):
    """Copy a tree of numpy arrays into shared memory; return the spec."""
    from multiprocessing import resource_tracker, shared_memory
    if isinstance(tree, tuple):
        return ("tuple", [_tree_to_shm(t) for t in tree])
    shm = shared_memory.SharedMemory(create=True, size=max(tree.nbytes, 1))
    onp.ndarray(tree.shape, tree.dtype, buffer=shm.buf)[...] = tree
    name = shm.name
    shm.close()
    # ownership transfers to the parent (it unlinks after upload); drop
    # the creating process's resource-tracker registration so worker
    # shutdown does not try to destroy segments it no longer owns
    try:
        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:  # mxlint: allow-broad-except(tracker unregister is best-effort; ownership already transferred to the parent)
        pass
    return ("array", name, tree.shape, str(tree.dtype))


def _tree_from_shm(spec, to_nd=True):
    """Rebuild the batch from shared memory, upload, unlink the segments."""
    from multiprocessing import shared_memory
    kind = spec[0]
    if kind == "tuple":
        return tuple(_tree_from_shm(s, to_nd) for s in spec[1])
    _, name, shape, dtype = spec
    shm = shared_memory.SharedMemory(name=name)
    try:
        view = onp.ndarray(shape, onp.dtype(dtype), buffer=shm.buf)
        out = nd.array(view) if to_nd else view.copy()
    finally:
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    return out


def _unlink_spec(spec):
    """Release the shared memory behind an undelivered batch spec."""
    from multiprocessing import shared_memory
    if spec[0] == "tuple":
        for s in spec[1]:
            _unlink_spec(s)
        return
    try:
        shm = shared_memory.SharedMemory(name=spec[1])
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass


def _worker_loop(dataset, batchify_fn, key_queue, result_queue):
    """Forked child: pull (seq, indices), push (seq, shm spec | error)."""
    while True:
        item = key_queue.get()
        if item is None:
            return
        seq, indices = item
        try:
            batch = batchify_fn([dataset[i] for i in indices])
            result_queue.put((seq, "ok", _tree_to_shm(batch)))
        except Exception:  # mxlint: allow-broad-except(worker failure ships to the parent as an error result with the traceback)
            result_queue.put((seq, "error", traceback.format_exc()))


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._timeout = timeout
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * max(num_workers, 1))
        if num_workers > 0:
            # prefetch=0 with active workers would submit zero batches
            # and both worker paths would silently yield an EMPTY
            # iterator (the whole dataset dropped, no error) — at least
            # one batch must be in flight for the pipeline to progress
            self._prefetch = max(1, self._prefetch)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if not self._thread_pool:
            yield from self._iter_multiprocess()
            return
        yield from self._iter_threads()

    def _iter_threads(self):
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch):
                    futures.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                batch = futures.pop(0).result()
                try:
                    futures.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass
                yield batch

    def _iter_multiprocess(self):
        """Forked numpy-only workers + shared-memory batch transport
        (reference dataloader.py:28-133 / cpu_shared_storage_manager.h
        analog).  Batches are yielded strictly in sampler order."""
        import multiprocessing as mp
        ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods()
                             else "spawn")
        batchify = (self._batchify_fn if self._batchify_fn
                    is not default_batchify_fn else _np_batchify)
        if ctx.get_start_method() == "spawn":
            # spawn ships worker args by pickle; a dataset/batchify with
            # closure or lambda transforms dies inside Process.start
            # with an opaque PicklingError — probe up front and fall
            # back to the thread pool with a clear warning instead.
            # dataset/batchify are fixed at construction, so probe ONCE
            # per loader (a full-dataset pickle per epoch is not free)
            ok = getattr(self, "_spawn_picklable", None)
            if ok is None:
                import pickle
                try:
                    pickle.dumps((self._dataset, batchify))
                    ok = True
                except Exception as e:  # mxlint: allow-broad-except(pickle probe: ANY serialization failure means spawn cannot work; the loader degrades to threads with a warning)
                    ok = False
                    self._spawn_pickle_error = f"{type(e).__name__}: {e}"
                self._spawn_picklable = ok
            if not ok:
                import warnings
                warnings.warn(
                    "multiprocess DataLoader needs picklable "
                    "dataset/batchify on spawn-only hosts "
                    f"({self._spawn_pickle_error}); falling back to the "
                    "thread pool (module-level functions instead of "
                    "lambdas/closures restore process workers)")
                yield from self._iter_threads()
                return
        key_queue = ctx.Queue()
        result_queue = ctx.Queue()
        workers = [ctx.Process(
            target=_worker_loop,
            args=(self._dataset, batchify, key_queue, result_queue),
            daemon=True) for _ in range(self._num_workers)]
        done = {}
        for w in workers:
            w.start()
        try:
            it = enumerate(iter(self._batch_sampler))
            sent = 0
            for _ in range(self._prefetch):
                try:
                    key_queue.put(next(it))
                    sent += 1
                except StopIteration:
                    break
            next_seq = 0
            # every submitted batch yields exactly once, in order —
            # `sent` only grows, so this drains the tail the prefetch
            # ramp left in `done`
            import queue as _q
            while next_seq < sent:
                while next_seq not in done:
                    try:
                        seq, status, payload = result_queue.get(
                            timeout=self._timeout)
                    except _q.Empty:
                        # distinguish "slow batch" from "worker died
                        # without reporting" (OOM-kill, segfault)
                        dead = [w.pid for w in workers if not w.is_alive()]
                        raise RuntimeError(
                            f"DataLoader timed out after {self._timeout}s "
                            f"waiting for batch {next_seq}"
                            + (f"; worker pid(s) {dead} died without "
                               "reporting" if dead else
                               " (workers alive — raise `timeout` for "
                               "slow augmentation)")) from None
                    if status == "error":
                        raise RuntimeError(
                            f"DataLoader worker failed on batch {seq}:\n"
                            f"{payload}")
                    done[seq] = payload
                    try:
                        key_queue.put(next(it))
                        sent += 1
                    except StopIteration:
                        pass
                yield _tree_from_shm(done.pop(next_seq))
                next_seq += 1
        finally:
            for _ in workers:
                key_queue.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
            # early abandonment leaves undelivered batches in shared
            # memory — release them (workers are stopped, so the drain
            # is complete)
            import queue as _queue
            try:
                while True:
                    _, status, payload = result_queue.get_nowait()
                    if status == "ok":
                        _unlink_spec(payload)
            except (_queue.Empty, OSError):
                pass
            for payload in done.values():
                _unlink_spec(payload)
            for q in (key_queue, result_queue):
                q.close()
                q.cancel_join_thread()

    def __len__(self):
        return len(self._batch_sampler)
