"""Vision datasets (reference gluon/data/vision/datasets.py).

Zero-egress environment: datasets load from a local ``root`` directory in
the standard file formats when present; otherwise they fall back to a
deterministic synthetic sample set (flagged via ``.synthetic``) so
training loops and tests run without network access.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from .... import ndarray as nd
from ..dataset import Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self.synthetic = False
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        data = self._data[idx]
        if self._transform is not None:
            return self._transform(data, self._label[idx])
        return data, self._label[idx]

    def _synthetic(self, shape, num_classes, n):
        rng = onp.random.RandomState(42 if self._train else 43)
        self._data = nd.array(
            rng.randint(0, 255, size=(n,) + shape).astype("uint8"))
        self._label = rng.randint(0, num_classes, size=(n,)).astype("int32")
        self.synthetic = True


class MNIST(_DownloadedDataset):
    """MNIST; reads idx-format files from root if available."""

    _files = {
        True: ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        False: ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None, synthetic_size=512):
        self._synthetic_size = synthetic_size
        super().__init__(root, train, transform)

    def _get_data(self):
        img_f, lbl_f = self._files[self._train]
        img_path = os.path.join(self._root, img_f)
        lbl_path = os.path.join(self._root, lbl_f)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = onp.frombuffer(f.read(), dtype=onp.uint8).astype("int32")
            with gzip.open(img_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                data = onp.frombuffer(f.read(), dtype=onp.uint8)
                data = data.reshape(n, rows, cols, 1)
            self._data = nd.array(data)
            self._label = label
        else:
            self._synthetic((28, 28, 1), 10, self._synthetic_size)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None, synthetic_size=512):
        super().__init__(root, train, transform, synthetic_size)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None, synthetic_size=512):
        self._synthetic_size = synthetic_size
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = [f"data_batch_{i}.bin" for i in range(1, 6)] \
            if self._train else ["test_batch.bin"]
        paths = [os.path.join(self._root, "cifar-10-batches-bin", b)
                 for b in batches]
        if all(os.path.exists(p) for p in paths):
            data, labels = [], []
            for p in paths:
                raw = onp.frombuffer(open(p, "rb").read(), dtype=onp.uint8)
                raw = raw.reshape(-1, 3073)
                labels.append(raw[:, 0].astype("int32"))
                data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                            .transpose(0, 2, 3, 1))
            self._data = nd.array(onp.concatenate(data))
            self._label = onp.concatenate(labels)
        else:
            self._synthetic((32, 32, 3), 10, self._synthetic_size)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 transform=None, fine_label=True, synthetic_size=512):
        self._fine = fine_label
        super().__init__(root, train, transform, synthetic_size)

    def _get_data(self):
        self._synthetic((32, 32, 3), 100, self._synthetic_size)


class ImageFolderDataset(Dataset):
    """Images arranged in class folders (reference datasets.py)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image
        fname, label = self.items[idx]
        img = image.imread(fname, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
