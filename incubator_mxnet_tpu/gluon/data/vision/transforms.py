"""Vision transforms (reference gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as onp

from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block
from ...nn.basic_layers import Sequential


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference transforms.ToTensor)."""

    def forward(self, x):
        out = x.astype("float32") / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, "float32").reshape(-1, 1, 1)
        self._std = onp.asarray(std, "float32").reshape(-1, 1, 1)

    def forward(self, x):
        return (x - nd.array(self._mean, ctx=x.ctx)) / \
            nd.array(self._std, ctx=x.ctx)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax.image
        h, w = self._size[1], self._size[0]
        if x.ndim == 3:
            out = jax.image.resize(x.data.astype("float32"),
                                   (h, w, x.shape[2]), method="bilinear")
        else:
            out = jax.image.resize(x.data.astype("float32"),
                                   (x.shape[0], h, w, x.shape[3]),
                                   method="bilinear")
        return NDArray(out.astype(x.data.dtype), ctx=x.ctx)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target = onp.random.uniform(*self._scale) * area
            ar = onp.random.uniform(*self._ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if w <= W and h <= H:
                x0 = onp.random.randint(0, W - w + 1)
                y0 = onp.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return Resize(self._size).forward(crop)
        return Resize(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[..., ::-1, :] if x.ndim == 3 else x
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return NDArray(x.data[::-1], ctx=x.ctx) if x.ndim == 3 else x
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._b, self._b)
        return (x.astype("float32") * alpha).clip(0, 255).astype(str(x.dtype))
