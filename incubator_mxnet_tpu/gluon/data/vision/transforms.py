"""Vision transforms (reference gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as onp

from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block
from ...nn.basic_layers import Sequential


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference transforms.ToTensor)."""

    def forward(self, x):
        out = x.astype("float32") / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, "float32").reshape(-1, 1, 1)
        self._std = onp.asarray(std, "float32").reshape(-1, 1, 1)

    def forward(self, x):
        return (x - nd.array(self._mean, ctx=x.ctx)) / \
            nd.array(self._std, ctx=x.ctx)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax.image
        h, w = self._size[1], self._size[0]
        if x.ndim == 3:
            out = jax.image.resize(x.data.astype("float32"),
                                   (h, w, x.shape[2]), method="bilinear")
        else:
            out = jax.image.resize(x.data.astype("float32"),
                                   (x.shape[0], h, w, x.shape[3]),
                                   method="bilinear")
        return NDArray(out.astype(x.data.dtype), ctx=x.ctx)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0, x0 = max((H - h) // 2, 0), max((W - w) // 2, 0)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target = onp.random.uniform(*self._scale) * area
            ar = onp.random.uniform(*self._ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if w <= W and h <= H:
                x0 = onp.random.randint(0, W - w + 1)
                y0 = onp.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return Resize(self._size).forward(crop)
        return Resize(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return x[..., ::-1, :] if x.ndim == 3 else x
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if onp.random.rand() < 0.5:
            return NDArray(x.data[::-1], ctx=x.ctx) if x.ndim == 3 else x
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._b, self._b)
        return (x.astype("float32") * alpha).clip(0, 255).astype(str(x.dtype))


_GRAY = onp.array([0.299, 0.587, 0.114], "float32")

_YIQ = onp.array([[0.299, 0.587, 0.114],
                  [0.596, -0.274, -0.321],
                  [0.211, -0.523, 0.311]], "float32")
_YIQ_INV = onp.linalg.inv(_YIQ).astype("float32")


class RandomContrast(Block):
    """alpha-blend with the LUMINANCE mean (reference
    ContrastJitterAug: gray = 0.299R+0.587G+0.114B, blend with its
    mean)."""

    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._c, self._c)
        f = x.astype("float32")
        lum_mean = float(nd.dot(f, nd.array(_GRAY, ctx=x.ctx))
                         .mean().asnumpy())
        return (f * alpha + lum_mean * (1 - alpha)) \
            .clip(0, 255).astype(str(x.dtype))


class RandomSaturation(Block):
    """alpha-blend with the per-pixel grayscale (reference
    RandomSaturation)."""

    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._s, self._s)
        f = x.astype("float32")
        gray = nd.dot(f, nd.array(_GRAY, ctx=x.ctx)).expand_dims(-1)
        return (f * alpha + gray * (1 - alpha)) \
            .clip(0, 255).astype(str(x.dtype))


class RandomHue(Block):
    """Rotate hue via the YIQ linear approximation (reference RandomHue's
    cv-free formulation)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        alpha = onp.random.uniform(-self._h, self._h) * onp.pi
        u, w = onp.cos(alpha), onp.sin(alpha)
        t_hue = onp.array([[1.0, 0.0, 0.0],
                           [0.0, u, -w],
                           [0.0, w, u]], "float32")
        t_rgb = _YIQ_INV @ t_hue @ _YIQ
        f = x.astype("float32")
        out = nd.dot(f, nd.array(t_rgb.T.astype("float32"), ctx=x.ctx))
        return out.clip(0, 255).astype(str(x.dtype))


class RandomColorJitter(Block):
    """Brightness/contrast/saturation/hue in random order (reference
    RandomColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        for i in onp.random.permutation(len(self._ts)):
            x = self._ts[int(i)].forward(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference RandomLighting)."""

    _eigval = onp.array([55.46, 4.794, 1.148], "float32")
    _eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], "float32")

    def __init__(self, alpha):
        super().__init__()
        self._a = alpha

    def forward(self, x):
        alpha = onp.random.normal(0, self._a, 3).astype("float32")
        rgb = (self._eigvec * alpha) @ self._eigval
        return (x.astype("float32") + nd.array(rgb, ctx=x.ctx)) \
            .clip(0, 255).astype(str(x.dtype))


class RandomGray(Block):
    """Random grayscale conversion with probability p (reference
    RandomGray)."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            f = x.astype("float32")
            gray = nd.dot(f, nd.array(_GRAY, ctx=x.ctx)).expand_dims(-1)
            return nd.concat(gray, gray, gray, dim=-1) \
                .clip(0, 255).astype(str(x.dtype))
        return x


class RandomCrop(Block):
    """Random-position crop with optional padding (reference
    RandomCrop): delegates to image.random_crop, which upscales when
    the (padded) source is smaller than the target so the output shape
    is always exactly ``size``.  HWC images only (the reference's
    contract; batches go through CenterCrop/batch-aware ops)."""

    def __init__(self, size, pad=None, pad_value=0, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad
        self._pad_value = pad_value
        self._interp = interpolation

    def forward(self, x):
        if x.ndim != 3:
            raise ValueError("RandomCrop expects an HWC image; use "
                             "CenterCrop for batched input")
        if self._pad:
            p = self._pad
            x = NDArray(onp.pad(onp.asarray(x.asnumpy()),
                                ((p, p), (p, p), (0, 0)),
                                constant_values=self._pad_value), ctx=x.ctx)
        from ....image import random_crop as _random_crop
        out, _ = _random_crop(x, self._size, interp=self._interp)
        return out


class CropResize(Block):
    """Fixed crop then resize (reference CropResize)."""

    def __init__(self, x0, y0, width, height, size=None, interpolation=1):
        super().__init__()
        self._box = (x0, y0, width, height)
        self._size = size
        self._interp = interpolation

    def forward(self, x):
        x0, y0, w, h = self._box
        crop = x[..., y0:y0 + h, x0:x0 + w, :]
        if self._size is not None:
            return Resize(self._size, interpolation=self._interp) \
                .forward(crop)
        return crop


class Rotate(Block):
    """Rotate by a fixed angle in DEGREES, zero-filled corners
    (reference transforms.Rotate) — bilinear gather via
    map_coordinates.  The reference's zoom_in/zoom_out modes are not
    implemented; passing them raises instead of silently producing
    un-zoomed output."""

    def __init__(self, rotation_degrees=None, zoom_in=False, zoom_out=False,
                 rotation=None):
        super().__init__()
        if zoom_in or zoom_out:
            raise NotImplementedError(
                "Rotate zoom_in/zoom_out are not implemented; rotate "
                "then Resize/CenterCrop explicitly")
        deg = rotation_degrees if rotation_degrees is not None else rotation
        self._theta = float(onp.deg2rad(deg if deg is not None else 0.0))

    def _rotate(self, x, theta):
        from jax.scipy.ndimage import map_coordinates
        import jax.numpy as jnp
        f = x.data.astype("float32")
        H, W = f.shape[0], f.shape[1]
        cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
        yy, xx = jnp.meshgrid(jnp.arange(H) - cy, jnp.arange(W) - cx,
                              indexing="ij")
        src_y = cy + yy * onp.cos(theta) - xx * onp.sin(theta)
        src_x = cx + yy * onp.sin(theta) + xx * onp.cos(theta)
        out = jnp.stack([
            map_coordinates(f[..., c], [src_y, src_x], order=1, cval=0.0)
            for c in range(f.shape[-1])], axis=-1)
        return NDArray(out.astype(x.data.dtype), ctx=x.ctx)

    def forward(self, x):
        return self._rotate(x, self._theta)


class RandomRotation(Rotate):
    """Rotate by a uniform random angle from [-a, a] degrees (reference
    RandomRotation)."""

    def __init__(self, angle_limits=(-10, 10), zoom_in=False,
                 zoom_out=False, rotate_with_proba=1.0):
        super().__init__(rotation_degrees=0.0, zoom_in=zoom_in,
                         zoom_out=zoom_out)
        self._limits = angle_limits
        self._proba = rotate_with_proba

    def forward(self, x):
        if onp.random.rand() >= self._proba:
            return x
        deg = onp.random.uniform(*self._limits)
        return self._rotate(x, float(onp.deg2rad(deg)))


class RandomApply(Block):
    """Apply a transform with probability p (reference RandomApply)."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        self._t = transforms
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            return self._t(x)
        return x


# every transform here routes through ops/NDArray methods, so the
# hybrid variants collapse to aliases (reference keeps separate
# HybridBlock hierarchies)
HybridCompose = Compose
HybridRandomApply = RandomApply
