"""Gluon: the define-by-run API (reference python/mxnet/gluon/)."""
from .block import Block, HybridBlock, SymbolBlock, CachedOp
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import metric
from . import data
from . import model_zoo
from . import utils
from . import contrib
from .utils import split_and_load
