"""Gluon contrib layers (reference python/mxnet/gluon/contrib/nn/
basic_layers.py): Concurrent/HybridConcurrent branching containers,
Identity, SparseEmbedding, SyncBatchNorm, PixelShuffle{1,2,3}D.
"""
from __future__ import annotations

import math

from ...block import HybridBlock
from ...nn import (Sequential, HybridSequential, Identity, Embedding,
                   BatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class _ConcurrentMixin:
    def _concat_branches(self, x):
        from ....ndarray import concat
        return concat(*[block(x) for block in self._children.values()],
                      dim=self.axis)


class Concurrent(_ConcurrentMixin, Sequential):
    """Feed the SAME input to every child and concat the outputs along
    ``axis`` (reference basic_layers.py Concurrent — the Inception-style
    branch container)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        return self._concat_branches(x)


class HybridConcurrent(_ConcurrentMixin, HybridSequential):
    """Hybridizable Concurrent (reference HybridConcurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        return self._concat_branches(x)


class SparseEmbedding(Embedding):
    """Embedding whose gradient is row-sparse (reference
    SparseEmbedding).  The row_sparse optimizer path (sgd lazy_update,
    ops/sparse_ops.py) consumes such gradients; under XLA the gather
    backward is already a scatter-add touching only the looked-up rows,
    so this is Embedding with the sparse-grad contract documented."""


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference contrib SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc).

    TPU-first: under pjit/shard_map with the batch axis sharded, the
    batch-stat reductions inside BatchNorm lower to mesh all-reduces
    automatically (GSPMD), so plain BatchNorm IS sync-BN there; this
    class keeps the reference signature (num_devices accepted, unused
    in-process).
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class _PixelShuffle(HybridBlock):
    _ndim = 2

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * self._ndim
        except TypeError:
            self._factors = tuple(int(f) for f in factor)
            if len(self._factors) != self._ndim:
                raise ValueError(f"wrong length {len(self._factors)}")
        self._prod = math.prod(self._factors)

    def forward(self, x):
        # route through the registered reshape/transpose ops so the
        # autograd tape records every step (a raw jnp rearrangement here
        # would silently drop gradients through the layer)
        from ....ndarray import reshape, transpose
        fs = self._factors
        nd_sp = self._ndim
        N = x.shape[0]
        C = x.shape[1] // self._prod
        spatial = tuple(x.shape[2:])
        # (N, f1*..*fk*C, *S) -> (N, C, f1..fk, *S): channel-major C
        # first, then factors (reference reshape(0, -4, -1, f1*f2, 0, 0))
        y = reshape(x, shape=(N, C) + fs + spatial)
        # interleave: (N, C, S1, f1, S2, f2, ...)
        perm = [0, 1]
        for i in range(nd_sp):
            perm += [2 + nd_sp + i, 2 + i]
        y = transpose(y, axes=tuple(perm))
        out_spatial = tuple(s * f for s, f in zip(spatial, fs))
        return reshape(y, shape=(N, C) + out_spatial)

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"


class PixelShuffle1D(_PixelShuffle):
    """(N, f*C, W) -> (N, C, f*W) (reference PixelShuffle1D)."""
    _ndim = 1


class PixelShuffle2D(_PixelShuffle):
    """(N, f1*f2*C, H, W) -> (N, C, f1*H, f2*W) (reference
    PixelShuffle2D — sub-pixel upsampling, arXiv:1609.05158)."""
    _ndim = 2


class PixelShuffle3D(_PixelShuffle):
    """(N, f1*f2*f3*C, D, H, W) -> (N, C, f1*D, f2*H, f3*W)."""
    _ndim = 3
