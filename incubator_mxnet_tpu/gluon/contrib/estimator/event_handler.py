"""Event handlers incl. checkpoint/resume (reference
gluon/contrib/estimator/event_handler.py:336 CheckpointHandler,
resume_from_checkpoint :371-403 — the framework's checkpoint-restart
recovery story, SURVEY.md §5.3/5.4)."""
from __future__ import annotations

import logging
import os
import time


class EventHandler:
    """Base marker for estimator event handlers (reference
    event_handler.py EventHandler); the mixin classes below define the
    hook points."""


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        return False


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        return False


class GradientUpdateHandler(BatchEnd):
    """Applies the optimizer step at batch end (reference
    event_handler.py GradientUpdateHandler) — pulled out of the fit
    loop so update cadence is overridable (e.g. gradient accumulation:
    subclass and step every N batches)."""

    def __init__(self, priority=-2000):
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        estimator.trainer.step(estimator._last_batch_size)


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch

    def batch_end(self, estimator, *args, **kwargs):
        return self.max_batch is not None and \
            estimator.batch_idx >= self.max_batch

    def epoch_end(self, estimator, *args, **kwargs):
        return self.max_epoch is not None and \
            estimator.current_epoch + 1 >= self.max_epoch


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        return False


class ValidationHandler(BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period

    def epoch_end(self, estimator, *args, **kwargs):
        if (estimator.current_epoch + 1) % self.epoch_period == 0:
            self.eval_fn(self.val_data)
        return False


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics

    def train_begin(self, estimator, *args, **kwargs):
        self._start = time.monotonic()
        logging.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        logging.info("Training end; total time %.1fs",
                     time.monotonic() - self._start)

    def epoch_end(self, estimator, *args, **kwargs):
        msgs = []
        for m in estimator.train_metrics + [estimator.train_loss_metric]:
            name, value = m.get()
            msgs.append(f"{name}={value:.4f}")
        logging.info("Epoch %d: %s", estimator.current_epoch, " ".join(msgs))
        return False


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic / best-k checkpointing with resume (reference :336-403)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.best = None
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator, *args, **kwargs):
        if self.resume_from_checkpoint:
            ckpts = sorted(
                f for f in os.listdir(self.model_dir)
                if f.startswith(self.model_prefix) and f.endswith(".params")
                and "epoch" in f)
            if ckpts:
                latest = ckpts[-1]
                epoch = int(latest.split("epoch")[1].split("-")[0]
                            .split(".")[0])
                estimator.net.load_parameters(
                    os.path.join(self.model_dir, latest))
                states = os.path.join(
                    self.model_dir, latest.replace(".params", ".states"))
                if os.path.exists(states):
                    estimator.trainer.load_states(states)
                estimator.current_epoch = epoch + 1
                logging.info("Resumed from %s (epoch %d)", latest, epoch)

    def _save(self, estimator, tag):
        base = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}")
        estimator.net.save_parameters(base + ".params")
        estimator.trainer.save_states(base + ".states")
        self.saved.append(base)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for ext in (".params", ".states"):
                try:
                    os.remove(old + ext)
                except FileNotFoundError:
                    pass

    def epoch_end(self, estimator, *args, **kwargs):
        if (estimator.current_epoch + 1) % self.epoch_period == 0:
            self._save(estimator, f"epoch{estimator.current_epoch}")
            if self.save_best and self.monitor is not None:
                name, value = self.monitor.get()
                if self.best is None or value > self.best:
                    self.best = value
                    base = os.path.join(self.model_dir,
                                        f"{self.model_prefix}-best")
                    estimator.net.save_parameters(base + ".params")
        return False


class EarlyStoppingHandler(EpochEnd):
    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.best = None
        self.wait = 0

    def epoch_end(self, estimator, *args, **kwargs):
        name, value = self.monitor.get()
        if self.best is None or value > self.best + self.min_delta:
            self.best = value
            self.wait = 0
            return False
        self.wait += 1
        return self.wait > self.patience


class AsyncCheckpointHandler(BatchEnd, TrainEnd):
    """Checkpointing that never stalls the train loop: snapshots the
    net's parameters through checkpoint.AsyncCheckpointManager every
    ``batch_period`` batches (device-side copy now, IO on a writer
    thread — SURVEY §5.4's sharded-async addition; CheckpointHandler
    above keeps the reference's synchronous .params behavior)."""

    def __init__(self, model_dir, batch_period=100, max_checkpoints=5):
        from ....checkpoint import AsyncCheckpointManager
        self.manager = AsyncCheckpointManager(model_dir,
                                              keep=max_checkpoints)
        self.batch_period = batch_period
        self._batches = 0

    def batch_end(self, estimator, *args, **kwargs):
        self._batches += 1
        if self._batches % self.batch_period == 0:
            params = {name: p.data()
                      for name, p in estimator.net.collect_params().items()
                      if p._data is not None}
            self.manager.save(self._batches, params)

    def train_end(self, estimator, *args, **kwargs):
        self.manager.wait()  # durable before exit

    def restore_into(self, net, step=None):
        """Load a snapshot back into a Block's parameters.

        Name mismatches are loud (load_parameters convention,
        block.py): zero matches raise, partial matches raise listing
        the missing names."""
        snap = self.manager.restore(step)
        params = net.collect_params()
        matched = [n for n in params if n in snap]
        if not matched:
            raise KeyError(
                f"no parameter names match the snapshot (net has "
                f"{sorted(params)[:5]}..., snapshot has "
                f"{sorted(snap)[:5]}...)")
        missing = [n for n in params if n not in snap]
        if missing:
            raise KeyError(
                f"snapshot is missing parameters: {missing[:10]}")
        for name in matched:
            params[name].set_data(snap[name])  # public API: coerces dtype
