from .estimator import Estimator, BatchProcessor
from .event_handler import (EventHandler, TrainBegin, TrainEnd, EpochBegin,
                            EpochEnd, BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler, LoggingHandler,
                            CheckpointHandler, EarlyStoppingHandler,
                            AsyncCheckpointHandler, GradientUpdateHandler)
