"""Estimator: high-level fit loop (reference gluon/contrib/estimator/estimator.py)."""
from __future__ import annotations

from .... import autograd
from ....context import current_context
from ... import metric as metric_mod
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            TrainBegin, TrainEnd, MetricHandler,
                            LoggingHandler, GradientUpdateHandler)


class BatchProcessor:
    """Encapsulates the per-batch forward/backward (reference
    batch_processor.py): override fit_batch/evaluate_batch to customize
    how a batch flows through the net (multi-input models, teacher
    forcing, ...)."""

    def fit_batch(self, estimator, batch, batch_axis=0):
        data, label = batch[0], batch[1]
        data = data.as_in_context(estimator.context)
        label = label if not hasattr(label, "as_in_context") \
            else label.as_in_context(estimator.context)
        with autograd.record():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label)
        loss.backward()
        return data, label, pred, loss

    def evaluate_batch(self, estimator, batch, batch_axis=0):
        data, label = batch[0], batch[1]
        data = data.as_in_context(estimator.context)
        pred = estimator.net(data)
        return data, label, pred


class Estimator:
    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, evaluation_loss=None,
                 batch_processor=None):
        self.batch_processor = batch_processor or BatchProcessor()
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or [metric_mod.Accuracy()]
        self.val_metrics = val_metrics or [metric_mod.Accuracy()]
        self.context = context or current_context()
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})
        self.evaluation_loss = evaluation_loss or loss
        self.train_loss_metric = metric_mod.Loss("train_loss")

    def prepare_loss_and_metrics(self):
        return self.train_metrics, self.val_metrics

    def evaluate(self, val_data, batch_axis=0):
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            _, label, pred = self.batch_processor.evaluate_batch(
                self, batch, batch_axis)
            for m in self.val_metrics:
                m.update([label], [pred])
        return {m.get()[0]: m.get()[1] for m in self.val_metrics}

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_axis=0):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, GradientUpdateHandler) for h in handlers):
            # the optimizer step is itself a handler (reference
            # estimator.py): prepend so it runs before metric/log hooks
            handlers.insert(0, GradientUpdateHandler())
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())
        for h in handlers:
            if hasattr(h, "bind"):
                h.bind(self)

        estimator_ref = self
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(estimator_ref)
        self.current_epoch = 0
        self.batch_idx = 0
        stop = False
        for epoch in range(epochs):
            self.current_epoch = epoch
            for h in handlers:
                if isinstance(h, EpochBegin):
                    h.epoch_begin(estimator_ref)
            for batch in train_data:
                for h in handlers:
                    if isinstance(h, BatchBegin):
                        h.batch_begin(estimator_ref, batch=batch)
                data, label, pred, loss = self.batch_processor.fit_batch(
                    self, batch, batch_axis)
                self._last_batch_size = data.shape[batch_axis]
                self.train_loss_metric.update(None, [loss])
                for m in self.train_metrics:
                    m.update([label], [pred])
                self.batch_idx += 1
                for h in handlers:
                    if isinstance(h, BatchEnd):
                        if h.batch_end(estimator_ref, batch=batch,
                                       pred=pred, label=label, loss=loss):
                            stop = True
                if stop:
                    break
            if val_data is not None:
                self.evaluate(val_data)
            for h in handlers:
                if isinstance(h, EpochEnd):
                    if h.epoch_end(estimator_ref):
                        stop = True
            if stop:
                break
        for h in handlers:
            if isinstance(h, TrainEnd):
                h.train_end(estimator_ref)
