"""Gluon contrib recurrent cells (reference
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py + rnn/conv_rnn_cell
LSTMPCell, VariationalDropoutCell): convolutional RNN/LSTM/GRU cells in
1/2/3 spatial dims, projected LSTM, and variational (per-sequence mask)
dropout.

TPU design note: the conv cells' gates are `Convolution` ops on NC*
layouts, so under `hybridize`/scan the whole recurrence lowers to XLA
convs on the MXU exactly like the dense cells lower to matmuls.
"""
from __future__ import annotations

from .... import initializer as init_mod
from ....ops.registry import invoke
from ...parameter import Parameter
from ...rnn.rnn_cell import RecurrentCell, ModifierCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "LSTMPCell", "VariationalDropoutCell"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvCellBase(RecurrentCell):
    """Shared plumbing: i2h/h2h convs with same-padding so the hidden
    state keeps the input's spatial shape (reference _BaseConvRNNCell)."""

    _gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), activation="tanh", ndim=2, **kwargs):
        super().__init__(**kwargs)
        self._ndim = ndim
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hc = hidden_channels
        self._act = activation
        ik = _tup(i2h_kernel, ndim)
        hk = _tup(h2h_kernel, ndim)
        for k in hk:
            if k % 2 == 0:
                raise ValueError("h2h_kernel must be odd for same-padding "
                                 f"(got {hk})")
        self._ik, self._hk = ik, hk
        self._ipad = tuple(k // 2 for k in ik)
        self._hpad = tuple(k // 2 for k in hk)
        G = self._gates
        cin = self._input_shape[0]
        self.i2h_weight = Parameter(
            "i2h_weight", shape=(G * hidden_channels, cin) + ik,
            init=init_mod.Xavier())
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(G * hidden_channels, hidden_channels) + hk,
            init=init_mod.Xavier())
        self.i2h_bias = Parameter("i2h_bias", shape=(G * hidden_channels,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(G * hidden_channels,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        spatial = self._input_shape[1:]
        shape = (batch_size, self._hc) + spatial
        n = {1: [shape], 2: [shape, shape]}[self._num_states]
        return [{"shape": s, "__layout__": "NC" + "DHW"[-self._ndim:]}
                for s in n]

    _num_states = 1

    def _convs(self, inputs, h):
        G = self._gates
        i2h = invoke("Convolution", inputs, self.i2h_weight.data(),
                     self.i2h_bias.data(), kernel=self._ik,
                     num_filter=G * self._hc, pad=self._ipad)
        h2h = invoke("Convolution", h, self.h2h_weight.data(),
                     self.h2h_bias.data(), kernel=self._hk,
                     num_filter=G * self._hc, pad=self._hpad)
        return i2h, h2h


class _ConvRNNCell(_ConvCellBase):
    _gates = 1
    _num_states = 1

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        out = invoke("Activation", i2h + h2h, act_type=self._act)
        return out, [out]


class _ConvLSTMCell(_ConvCellBase):
    _gates = 4
    _num_states = 2

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        gates = i2h + h2h
        i, f, g, o = invoke("split", gates, num_outputs=4, axis=1)
        c = invoke("sigmoid", f) * states[1] + \
            invoke("sigmoid", i) * invoke("Activation", g,
                                          act_type=self._act)
        h = invoke("sigmoid", o) * invoke("Activation", c,
                                          act_type=self._act)
        return h, [h, c]


class _ConvGRUCell(_ConvCellBase):
    _gates = 3
    _num_states = 1

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        i_r, i_z, i_n = invoke("split", i2h, num_outputs=3, axis=1)
        h_r, h_z, h_n = invoke("split", h2h, num_outputs=3, axis=1)
        r = invoke("sigmoid", i_r + h_r)
        z = invoke("sigmoid", i_z + h_z)
        n = invoke("Activation", i_n + r * h_n, act_type=self._act)
        h = (1 - z) * n + z * states[0]
        return h, [h]


def _make(ndim, base, name, doc):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                     h2h_kernel=3, activation="tanh", **kwargs):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, activation, ndim=ndim, **kwargs)

    Cell.__name__ = Cell.__qualname__ = name
    Cell.__doc__ = doc
    return Cell


Conv1DRNNCell = _make(1, _ConvRNNCell, "Conv1DRNNCell",
                      "1-D convolutional RNN cell (NCW states).")
Conv2DRNNCell = _make(2, _ConvRNNCell, "Conv2DRNNCell",
                      "2-D convolutional RNN cell (NCHW states).")
Conv3DRNNCell = _make(3, _ConvRNNCell, "Conv3DRNNCell",
                      "3-D convolutional RNN cell (NCDHW states).")
Conv1DLSTMCell = _make(1, _ConvLSTMCell, "Conv1DLSTMCell",
                       "1-D ConvLSTM cell (Shi et al. 2015).")
Conv2DLSTMCell = _make(2, _ConvLSTMCell, "Conv2DLSTMCell",
                       "2-D ConvLSTM cell (Shi et al. 2015).")
Conv3DLSTMCell = _make(3, _ConvLSTMCell, "Conv3DLSTMCell",
                       "3-D ConvLSTM cell (Shi et al. 2015).")
Conv1DGRUCell = _make(1, _ConvGRUCell, "Conv1DGRUCell",
                      "1-D convolutional GRU cell.")
Conv2DGRUCell = _make(2, _ConvGRUCell, "Conv2DGRUCell",
                      "2-D convolutional GRU cell.")
Conv3DGRUCell = _make(3, _ConvGRUCell, "Conv3DGRUCell",
                      "3-D convolutional GRU cell.")


class LSTMPCell(RecurrentCell):
    """LSTM with a projection layer on the hidden state (reference
    contrib LSTMPCell; Sak et al. 2014): c stays hidden_size wide, h is
    projected to projection_size before recurrence and output."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._proj = projection_size
        H, P = hidden_size, projection_size
        self.i2h_weight = Parameter("i2h_weight", shape=(4 * H, input_size),
                                    init=init_mod.Xavier(),
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(4 * H, P),
                                    init=init_mod.Xavier())
        self.h2r_weight = Parameter("h2r_weight", shape=(P, H),
                                    init=init_mod.Xavier())
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * H,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * H,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._proj), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size,
                                     inputs.shape[-1])
            self.i2h_weight._finish_deferred_init()
        H = self._hidden_size
        gates = invoke("FullyConnected", inputs, self.i2h_weight.data(),
                       self.i2h_bias.data(), num_hidden=4 * H,
                       flatten=False) + \
            invoke("FullyConnected", states[0], self.h2h_weight.data(),
                   self.h2h_bias.data(), num_hidden=4 * H, flatten=False)
        i, f, g, o = invoke("split", gates, num_outputs=4, axis=-1)
        c = invoke("sigmoid", f) * states[1] + \
            invoke("sigmoid", i) * invoke("tanh", g)
        h_full = invoke("sigmoid", o) * invoke("tanh", c)
        r = invoke("FullyConnected", h_full, self.h2r_weight.data(), None,
                   num_hidden=self._proj, no_bias=True, flatten=False)
        return r, [r, c]


class VariationalDropoutCell(ModifierCell):
    """Applies the SAME dropout mask at every time step (reference
    contrib VariationalDropoutCell; Gal & Ghahramani 2016) to inputs,
    states, and outputs of the wrapped cell."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(base_cell, **kwargs)
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self.reset_masks()

    def reset_masks(self):
        self._masks = {}

    def begin_state(self, batch_size=0, **kwargs):
        self.reset_masks()  # new sequence → new masks
        return self.base_cell.begin_state(batch_size, **kwargs)

    def _mask(self, key, rate, like):
        from .... import autograd, random as _random, ndarray as nd_mod
        if not rate or not autograd.is_training():
            return None
        if key not in self._masks:
            keep = 1.0 - rate
            bern = nd_mod.random.bernoulli(keep, like.shape, ctx=like.ctx)
            self._masks[key] = bern / keep
        return self._masks[key]

    def forward(self, inputs, states):
        m = self._mask("i", self._di, inputs)
        if m is not None:
            inputs = inputs * m
        ms = self._mask("s", self._ds, states[0])
        if ms is not None:
            states = [states[0] * ms] + list(states[1:])
        out, new_states = self.base_cell(inputs, states)
        mo = self._mask("o", self._do, out)
        if mo is not None:
            out = out * mo
        return out, new_states
