"""Gluon contrib (reference python/mxnet/gluon/contrib/)."""
from . import cnn
from . import data
from . import estimator
from . import nn
from . import rnn
from .fuse_bn import fuse_conv_bn
