"""Gluon contrib data utilities (reference
python/mxnet/gluon/contrib/data/): IntervalSampler and the WikiText
language-modelling datasets.
"""
from __future__ import annotations

import os

import numpy as onp

from ...data.dataset import Dataset
from ...data.sampler import Sampler

__all__ = ["IntervalSampler", "WikiText2", "WikiText103"]


class IntervalSampler(Sampler):
    """Samples [i, i+interval, ...] for each phase i (reference
    contrib/data/sampler.py IntervalSampler)."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise ValueError(
                f"Interval {interval} must be <= length {length}")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length


class _WikiText(Dataset):
    """Token-id sequence dataset over a local WikiText dump (reference
    contrib/data/text.py _WikiText).  This environment has no network
    egress, so the archive must already exist under ``root`` (the
    reference auto-downloads); vocabulary is built from the train split
    on first use."""

    _filename: str

    def __init__(self, root, segment="train", seq_len=35):
        path = os.path.join(os.path.expanduser(root),
                            self._filename.format(segment))
        if not os.path.exists(path):
            raise OSError(
                f"{path} not found. Download is unavailable (no network "
                "egress); place the extracted WikiText .tokens files "
                f"under {root!r}.")
        with open(path, encoding="utf-8") as f:
            tokens = f.read().replace("\n", " <eos> ").split()
        vocab_src = path if segment == "train" else os.path.join(
            os.path.expanduser(root), self._filename.format("train"))
        if vocab_src == path:
            vtokens = tokens
        elif os.path.exists(vocab_src):
            with open(vocab_src, encoding="utf-8") as f:
                vtokens = f.read().replace("\n", " <eos> ").split()
        else:
            # a test/valid-only vocab would silently mismatch any model
            # trained with the train-split vocab — refuse instead
            raise OSError(
                f"{vocab_src} not found: the vocabulary is built from the "
                f"train split; place wiki.train.tokens next to {path}")
        self.vocab = {"<unk>": 0}
        for t in vtokens:
            self.vocab.setdefault(t, len(self.vocab))
        ids = onp.asarray([self.vocab.get(t, 0) for t in tokens],
                          onp.int32)
        n = (len(ids) - 1) // seq_len
        self._data = ids[:n * seq_len].reshape(n, seq_len)
        self._label = ids[1:n * seq_len + 1].reshape(n, seq_len)

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._data)


class WikiText2(_WikiText):
    """WikiText-2 (reference contrib/data/text.py WikiText2)."""
    _filename = "wiki.{}.tokens"


class WikiText103(_WikiText):
    """WikiText-103 (reference contrib/data/text.py WikiText103)."""
    _filename = "wiki.{}.tokens"
