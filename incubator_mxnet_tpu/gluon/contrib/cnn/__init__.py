"""Gluon contrib CNN layers (reference
python/mxnet/gluon/contrib/cnn/conv_layers.py): deformable convolution
blocks bundling the learned offset branch with the sampled conv.
"""
from __future__ import annotations

from .... import initializer as init_mod
from ....ops.registry import invoke
from ...block import HybridBlock
from ...nn import Conv2D
from ...parameter import Parameter

from ...nn.conv_layers import _tuple

__all__ = ["DeformableConvolution", "ModulatedDeformableConvolution"]


def _pair(v):
    return _tuple(v, 2)


class DeformableConvolution(HybridBlock):
    """2-D deformable convolution v1 (Dai 2017; reference
    conv_layers.py:29).  The offset field is produced by an internal
    zero-initialized Conv2D — so training starts as a plain conv — and
    consumed by the ``DeformableConvolution`` op."""

    _op_name = "DeformableConvolution"
    _mask = False

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, use_bias=True, in_channels=0,
                 activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        k = _pair(kernel_size)
        self._channels = channels
        self._groups = groups
        self._activation = activation
        self._use_bias = use_bias
        self._kwargs = dict(kernel=k, stride=_pair(strides),
                            pad=_pair(padding), dilate=_pair(dilation),
                            num_filter=channels, num_group=groups,
                            num_deformable_group=num_deformable_group,
                            no_bias=not use_bias)
        planes = k[0] * k[1] * num_deformable_group
        planes *= 3 if self._mask else 2
        self.offset = Conv2D(
            planes, kernel_size=k, strides=strides, padding=padding,
            dilation=dilation, use_bias=offset_use_bias,
            in_channels=in_channels,
            weight_initializer=offset_weight_initializer or
            init_mod.Zero(),
            bias_initializer=offset_bias_initializer)
        wshape = (channels, (in_channels // groups) if in_channels else 0) \
            + k
        self.weight = Parameter("weight", shape=wshape,
                                init=weight_initializer or init_mod.Xavier(),
                                allow_deferred_init=True)
        self.bias = Parameter("bias", shape=(channels,),
                              init=bias_initializer or init_mod.Zero(),
                              allow_deferred_init=True) if use_bias else None

    def _ensure_init(self, x):
        if self.weight._data is None:
            self.weight.shape = (self._channels,
                                 x.shape[1] // self._groups) \
                + self._kwargs["kernel"]
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()

    def forward(self, x):
        self._ensure_init(x)
        off = self.offset(x)
        k = self._kwargs["kernel"]
        ndg = self._kwargs["num_deformable_group"]
        args = [x]
        if self._mask:
            from ....ndarray import sigmoid, slice_axis
            n_off = 2 * k[0] * k[1] * ndg
            # reference conv_layers.py:383: mask = sigmoid(raw) * 2, so
            # a zero-initialized offset branch starts at mask 1.0 — the
            # layer begins as an exact plain convolution
            args += [slice_axis(off, axis=1, begin=0, end=n_off),
                     sigmoid(slice_axis(off, axis=1, begin=n_off,
                                        end=None)) * 2]
        else:
            args += [off]
        args += [self.weight.data()]
        if self._use_bias:
            args.append(self.bias.data())
        out = invoke(self._op_name, *args, **self._kwargs)
        if self._activation:
            out = invoke("Activation", out, act_type=self._activation)
        return out


class ModulatedDeformableConvolution(DeformableConvolution):
    """Deformable conv v2 (Zhu 2018; reference conv_layers.py
    ModulatedDeformableConvolution): the offset branch also emits a
    sigmoid-squashed per-tap modulation mask."""

    _op_name = "ModulatedDeformableConvolution"
    _mask = True
