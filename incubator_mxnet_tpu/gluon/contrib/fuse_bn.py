"""Conv+BatchNorm folding for inference deployment.

The classic eval-time transform (reference analog: the MKLDNN/TensorRT
subgraph fusers fold BN into the preceding conv,
src/operator/subgraph/mkldnn/mkldnn_conv-inl.h): with frozen moving
stats, ``BN(conv(x, W)) == conv(x, W * s) + b`` where

    s = gamma / sqrt(moving_var + eps)        (per out-channel)
    b = beta - moving_mean * s

Folding rewrites the conv's weights/bias in place and replaces the
BatchNorm with Identity, removing one elementwise pass over the
activation per conv — real bandwidth on a TPU inference sweep, and the
form quantization calibrators prefer (one int8 op instead of two).

Inference-only by contract: training a folded net is wrong (batch
stats are gone).  Works on any Block tree whose conv->BN pairs are
adjacent children in declaration order with conv feeding the BN — true
of every model-zoo family here, including the pre-activation V2 resnets
(their conv_i is declared right before the bn_{i+1} it feeds).
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp

from ... import nd
from ..nn import basic_layers as _bl
from ..nn.conv_layers import _Conv

__all__ = ["fuse_conv_bn"]


def _fold_pair(conv, bn):
    gamma = bn.gamma.data().data.astype(jnp.float32)
    beta = bn.beta.data().data.astype(jnp.float32)
    mean = bn.running_mean.data().data.astype(jnp.float32)
    var = bn.running_var.data().data.astype(jnp.float32)
    if not bn._scale:
        gamma = jnp.ones_like(gamma)
    if not bn._center:
        beta = jnp.zeros_like(beta)
    s = gamma / jnp.sqrt(var + bn._epsilon)
    b = beta - mean * s

    w = conv.weight.data().data
    # out-channel axis is 0 for both OIHW and O*K*I layouts
    bshape = (s.shape[0],) + (1,) * (w.ndim - 1)
    conv.weight.set_data(nd.NDArray((w.astype(jnp.float32)
                                     * s.reshape(bshape)).astype(w.dtype)))
    from ..parameter import Parameter
    if conv.bias is None:
        # conv layers built with use_bias=False gain a bias parameter
        p = Parameter("bias", shape=(int(s.shape[0]),))
        p.initialize()
        p.set_data(nd.NDArray(b.astype(w.dtype)))
        conv.bias = p
        conv._use_bias = True
    else:
        old = conv.bias.data().data.astype(jnp.float32)
        conv.bias.set_data(nd.NDArray((old * s + b).astype(
            conv.bias.data().data.dtype)))


def fuse_conv_bn(net):
    """Fold every adjacent Conv->BatchNorm pair under ``net`` in place
    (inference-only transform); returns the count of folded pairs."""
    folded = 0

    def walk(block):
        nonlocal folded
        children = list(block._children.items())
        for i, (name, child) in enumerate(children):
            if (isinstance(child, _Conv) and not child._transpose
                    and child._activation is None  # activation runs AFTER
                    # the conv: folding would reorder BN around it
                    and i + 1 < len(children)):
                nxt_name, nxt = children[i + 1]
                # exact type: BatchNormReLU has a relu inside — folding
                # it to Identity would silently drop the activation
                if type(nxt) is _bl.BatchNorm and \
                        nxt.running_mean._data is not None and \
                        child.weight._data is not None:
                    pairs.append(f"{name}->{nxt_name}")
                    _fold_pair(child, nxt)
                    setattr(block, nxt_name, _bl.Identity())
                    folded += 1
        for _, child in block._children.items():
            walk(child)

    pairs = []
    walk(net)
    if pairs:
        # pairing is by declaration-order adjacency, not dataflow
        # (correct for every zoo family); ONE summary warning makes a
        # misapplication on a custom Block visible without drowning the
        # common path in per-pair noise (ADVICE r4 + review) — verify
        # with a probe tensor if unsure
        warnings.warn(
            f"fuse_conv_bn folded {folded} conv->BN pair(s) by "
            f"declaration-order adjacency: {', '.join(pairs[:8])}"
            + (", ..." if len(pairs) > 8 else "")
            + " — verify dataflow adjacency on custom (non-zoo) blocks",
            stacklevel=2)
    return folded
