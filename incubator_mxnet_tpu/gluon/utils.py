"""Gluon utilities (reference python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Slice a batch along batch_axis into num_slice chunks
    (reference utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"batch size {size} not divisible by {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their joint L2 norm ≤ max_norm
    (reference utils.py clip_global_norm)."""
    total = jnp.zeros(())
    for a in arrays:
        total = total + jnp.sum(jnp.square(a.data.astype(jnp.float32)))
    norm = jnp.sqrt(total)
    scale = jnp.minimum(max_norm / (norm + 1e-12), 1.0)
    for a in arrays:
        a._set_data(a.data * scale.astype(a.data.dtype))
    return float(norm) if check_isfinite else NDArray(norm)


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise RuntimeError("network egress is unavailable; provide local files")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)
