"""Gluon utilities (reference python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "convert_conv_params_layout",
           "download", "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Slice a batch along batch_axis into num_slice chunks
    (reference utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"batch size {size} not divisible by {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their joint L2 norm ≤ max_norm
    (reference utils.py clip_global_norm)."""
    total = jnp.zeros(())
    for a in arrays:
        total = total + jnp.sum(jnp.square(a.data.astype(jnp.float32)))
    norm = jnp.sqrt(total)
    scale = jnp.minimum(max_norm / (norm + 1e-12), 1.0)
    for a in arrays:
        a._set_data(a.data * scale.astype(a.data.dtype))
    return float(norm) if check_isfinite else NDArray(norm)


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise RuntimeError("network egress is unavailable; provide local files")


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)


def convert_conv_params_layout(src_net, dst_net):
    """Copy parameters from ``src_net`` into ``dst_net`` across a conv
    data-layout change (NCHW <-> NHWC zoo nets): conv kernels are
    transposed OIHW <-> OHWI when the two layers' layouts differ.

    Which parameters are conv kernels is decided from the LAYERS (their
    channel-minor flag), never from shapes — an (O,3,3,3) kernel is
    shape-identical in both layouts and a shape heuristic would silently
    copy it untransposed.  Both nets must have resolved shapes (run one
    forward each).  Use this to move a reference-era NCHW checkpoint
    onto the NHWC fast path (``resnet50_v1(layout="NHWC", fused=True)``).
    """
    from .nn.conv_layers import _Conv

    def conv_weight_layouts(net):
        out = {}

        def walk(b):
            if isinstance(b, _Conv) and not b._transpose:
                out[id(b.weight)] = b._channel_minor
            for c in getattr(b, "_children", {}).values():
                walk(c)
        walk(net)
        return out

    src_cm = conv_weight_layouts(src_net)
    dst_cm = conv_weight_layouts(dst_net)
    sp = src_net.collect_params()
    dp = dst_net.collect_params()
    missing = [k for k in sp if k not in dp]
    extra = [k for k in dp if k not in sp]
    if missing or extra:
        raise ValueError(
            f"parameter sets differ: missing in dst {missing[:5]}, "
            f"only in dst {extra[:5]}")
    for name, p in sp.items():
        q = dp[name]
        s_minor = src_cm.get(id(p))
        d_minor = dst_cm.get(id(q))
        if s_minor is not None and d_minor is not None \
                and s_minor != d_minor:
            # rank-derived permutation (ADVICE r4): works for Conv1D
            # (OWI), Conv2D (OHWI) and Conv3D (ODHWI) kernels alike
            ndim = len(p.shape)
            if d_minor:        # O, spatial..., I  <-  O, I, spatial...
                perm = (0,) + tuple(range(2, ndim)) + (1,)
            else:              # O, I, spatial...  <-  O, spatial..., I
                perm = (0, ndim - 1) + tuple(range(1, ndim - 1))
            q.set_data(nd.transpose(p.data(), perm))
        elif p.shape != q.shape:
            raise ValueError(
                f"{name}: shape {p.shape} does not match destination "
                f"{q.shape} and is not a layout-differing conv kernel")
        else:
            q.set_data(p.data())
