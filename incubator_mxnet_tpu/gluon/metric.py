"""Evaluation metrics (reference python/mxnet/gluon/metric.py, 1,930 LoC)."""
from __future__ import annotations

import math

import numpy as onp

from ..base import registry
from ..ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Fbeta", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "Perplexity", "PearsonCorrelation",
           "PCC", "BinaryAccuracy", "MeanPairwiseDistance",
           "MeanCosineSimilarity", "Torch", "Caffe", "Loss", "CustomMetric",
           "create", "np"]

_reg = registry("metric")


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _reg.create(metric, *args, **kwargs)


class EvalMetric:
    """Base metric (reference metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label_dict, pred_dict):
        labels = [label_dict[n] for n in (self.label_names or label_dict)]
        preds = [pred_dict[n] for n in (self.output_names or pred_dict)]
        self.update(labels, preds)

    def __str__(self):
        return f"EvalMetric: {dict([self.get_name_value()[0]])}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _to_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@_reg.register(name="acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


_reg.alias("accuracy")(Accuracy)


@_reg.register(name="top_k_accuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).astype("int32").ravel()
            pred = _as_np(pred)
            topk = onp.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += (topk == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@_reg.register(name="f1")
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.threshold = threshold
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).ravel().astype("int32")
            pred = _as_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).ravel()
            else:
                pred = (pred.ravel() > self.threshold).astype("int32")
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return self.name, f1


@_reg.register(name="mcc")
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.tp = self.fp = self.fn = self.tn = 0

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = self.tn = 0

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).ravel().astype("int32")
            pred = _as_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype("int32")
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            self.num_inst += 1

    def get(self):
        num = self.tp * self.tn - self.fp * self.fn
        den = math.sqrt(max(
            (self.tp + self.fp) * (self.tp + self.fn) *
            (self.tn + self.fp) * (self.tn + self.fn), 1))
        return self.name, num / den


@_reg.register(name="mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += onp.abs(label - pred.reshape(label.shape)).mean() * len(label)
            self.num_inst += len(label)


@_reg.register(name="mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            self.sum_metric += ((label - pred.reshape(label.shape)) ** 2).mean() * len(label)
            self.num_inst += len(label)


@_reg.register(name="rmse")
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, math.sqrt(value) if not math.isnan(value) else value


@_reg.register(name="ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).ravel().astype("int64")
            pred = _as_np(pred)
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


_reg.alias("cross-entropy")(CrossEntropy)


@_reg.register(name="nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@_reg.register(name="perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).astype("int64").ravel()
            pred = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            prob = pred[onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += -onp.log(onp.maximum(prob, 1e-10)).sum()
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@_reg.register(name="pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels = []
        self._preds = []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            self._labels.append(_as_np(label).ravel())
            self._preds.append(_as_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        lab = onp.concatenate(self._labels)
        pred = onp.concatenate(self._preds)
        return self.name, float(onp.corrcoef(lab, pred)[0, 1])


@_reg.register(name="pcc")
class PCC(EvalMetric):
    """Multiclass Pearson correlation on the confusion matrix
    (reference metric.py:1651) — the K-class generalization of MCC."""

    def __init__(self, name="pcc", **kwargs):
        super().__init__(name, **kwargs)
        self.reset()

    def reset(self):
        super().reset()
        self._cm = onp.zeros((0, 0), onp.float64)

    def _grow(self, k):
        if k > self._cm.shape[0]:
            cm = onp.zeros((k, k), onp.float64)
            cm[:self._cm.shape[0], :self._cm.shape[1]] = self._cm
            self._cm = cm

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).ravel().astype("int64")
            pred = _as_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(-1).ravel()
            pred = pred.astype("int64")
            k = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            self._grow(k)
            onp.add.at(self._cm, (label, pred), 1.0)
            self.num_inst += label.size

    def get(self):
        c = self._cm
        if self.num_inst == 0 or c.size == 0:
            return self.name, float("nan")
        n = c.sum()
        t = c.sum(axis=1)   # true occurrences per class
        p = c.sum(axis=0)   # predicted occurrences per class
        cov_tp = onp.trace(c) * n - (t * p).sum()
        cov_tt = n * n - (t * t).sum()
        cov_pp = n * n - (p * p).sum()
        denom = math.sqrt(max(cov_tt * cov_pp, 0.0))
        return self.name, float(cov_tp / denom) if denom else float("nan")


@_reg.register(name="fbeta")
class Fbeta(F1):
    """Fbeta score for binary classification (reference metric.py:815):
    (1+beta^2) * P * R / (beta^2 * P + R)."""

    def __init__(self, name="fbeta", beta=1.0, threshold=0.5, **kwargs):
        self.beta = beta
        super().__init__(name=name, threshold=threshold, **kwargs)

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        b2 = self.beta ** 2
        denom = b2 * prec + rec
        fbeta = (1 + b2) * prec * rec / denom if denom else 0.0
        return self.name, fbeta


@_reg.register(name="binary_accuracy")
class BinaryAccuracy(EvalMetric):
    """Binary/multilabel accuracy at a threshold (reference
    metric.py:876)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label).ravel()
            pred = (_as_np(pred).ravel() > self.threshold)
            self.sum_metric += float((pred == (label > 0.5)).sum())
            self.num_inst += label.size


@_reg.register(name="mpd")
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between rows (reference metric.py:1197)."""

    def __init__(self, name="mpd", p=2.0, **kwargs):
        super().__init__(name, **kwargs)
        self.p = p

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            diff = onp.abs(pred.reshape(pred.shape[0], -1)
                           - label.reshape(label.shape[0], -1)) ** self.p
            dist = diff.sum(axis=1) ** (1.0 / self.p)
            self.sum_metric += float(dist.sum())
            self.num_inst += pred.shape[0]


@_reg.register(name="cos_sim")
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis (reference
    metric.py:1263)."""

    def __init__(self, name="cos_sim", eps=1e-12, **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            num = (label * pred).sum(axis=-1)
            den = onp.maximum(
                onp.linalg.norm(label, axis=-1)
                * onp.linalg.norm(pred, axis=-1), self.eps)
            sim = num / den
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size



@_reg.register(name="loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _to_list(preds):
            loss = _as_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            value = self._feval(_as_np(label), _as_np(pred))
            if isinstance(value, tuple):
                s, n = value
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += value
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", name)
    return CustomMetric(feval, name=feval.__name__,
                        allow_extra_outputs=allow_extra_outputs)
@_reg.register(name="torch")
class Torch(Loss):
    """Legacy alias (reference metric.py Torch: Loss-style mean)."""

    def __init__(self, name="torch", **kwargs):
        super().__init__(name=name, **kwargs)


@_reg.register(name="caffe")
class Caffe(Loss):
    """Legacy alias (reference metric.py Caffe)."""

    def __init__(self, name="caffe", **kwargs):
        super().__init__(name=name, **kwargs)
