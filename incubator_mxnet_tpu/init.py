"""``mx.init`` alias namespace (reference exposes initializers there too)."""
from .initializer import (  # noqa: F401
    Initializer, Zero, One, Constant, Uniform, Normal, Orthogonal, Xavier,
    MSRAPrelu, Bilinear, LSTMBias, Mixed, register, create,
)
