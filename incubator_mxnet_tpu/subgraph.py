"""Subgraph backend API (reference src/operator/subgraph/
subgraph_property.h:86-252, build_subgraph.cc, MXNET_SUBGRAPH_BACKEND).

Extension point parity: a backend registers a ``SubgraphProperty`` whose
selector claims ops; ``partition()`` greedily grows connected regions of
claimed nodes and replaces each with a single fused node executing the
sub-DAG through one ``jax.jit`` callable. The built-in ``"XLA"`` backend
claims every op — the whole-graph → one-XLA-program compile that
``simple_bind`` also performs, exposed through the same plugin surface
the reference uses for MKLDNN/TensorRT backends.
"""
from __future__ import annotations

import os
import threading

import jax
from .locks import named_lock

__all__ = ["SubgraphSelector", "SubgraphProperty", "register_backend",
           "get_backend", "list_backends", "partition",
           "default_backend_from_env"]

_BACKENDS: dict = {}
_lock = named_lock("subgraph.backends")


class SubgraphSelector:
    """Node-claiming policy (subgraph_property.h SubgraphSelector)."""

    def is_op_supported(self, node) -> bool:  # node: symbol._SymNode
        return False


class SubgraphProperty:
    """Backend description (subgraph_property.h SubgraphProperty)."""

    name = "base"

    def create_selector(self) -> SubgraphSelector:
        return SubgraphSelector()

    def min_subgraph_size(self) -> int:
        return 2

    # hook: backends may post-process the fused callable
    def wrap_callable(self, fn):
        return fn


def register_backend(prop: "SubgraphProperty | type"):
    """MXNET_REGISTER_SUBGRAPH_PROPERTY analog."""
    if isinstance(prop, type):
        prop = prop()
    with _lock:
        _BACKENDS[prop.name] = prop
    return prop


def get_backend(name: str) -> SubgraphProperty:
    try:
        return _BACKENDS[name]
    except KeyError:
        raise KeyError(
            f"subgraph backend {name!r} not registered "
            f"(have: {sorted(_BACKENDS)})") from None


def list_backends():
    return sorted(_BACKENDS)


def default_backend_from_env():
    """MXNET_SUBGRAPH_BACKEND env knob (reference
    docs faq/perf.md:61 / build_subgraph.cc)."""
    return os.environ.get("MXNET_SUBGRAPH_BACKEND", "")


class _AllSelector(SubgraphSelector):
    def is_op_supported(self, node):
        return True


class XLAProperty(SubgraphProperty):
    """Swallow the maximal subgraph into one XLA program (SURVEY.md §2.1
    subgraph row: the natural home of whole-graph compilation)."""

    name = "XLA"

    def create_selector(self):
        return _AllSelector()

    def min_subgraph_size(self):
        return 1


register_backend(XLAProperty)


_FUSED_UID = [0]


def partition(sym, backend_name):
    """Partition a Symbol under a backend: contiguous regions of claimed
    ops become fused nodes (reference build_subgraph.cc BuildSubgraph).

    Returns a new Symbol whose fused regions execute as single jitted
    callables through per-partition registered ops. Grouping is
    cycle-safe: a claimed node only joins an input's group when that
    group is not also reachable through an unclaimed path (otherwise the
    fused node would depend on an external input that depends on it).
    """
    from . import symbol as sym_mod
    from .ops.registry import register

    prop = get_backend(backend_name)
    selector = prop.create_selector()
    order = sym._topo_order()

    claimed = {n.key for n in order
               if n.op_name is not None and selector.is_op_supported(n)}

    # group assignment in topo order with cycle check:
    #   all_groups[v]    = groups reachable from v (any path)
    #   via_unclaimed[v] = groups reachable only via ≥1 unclaimed node
    group_of: dict = {}
    members_of: dict = {}
    all_groups: dict = {}
    via_unclaimed: dict = {}
    next_gid = [0]
    for n in order:
        ag, vu = set(), set()
        for i in n.inputs:
            ag |= all_groups.get(i.key, set())
            if i.key in claimed:
                vu |= via_unclaimed.get(i.key, set())
            else:
                # path through an unclaimed node: everything reachable
                # from it becomes forbidden for joining
                vu |= all_groups.get(i.key, set())
                vu |= via_unclaimed.get(i.key, set())
        if n.key in claimed:
            joined = None
            for i in n.inputs:
                g = group_of.get(i.key)
                if g is not None and g not in vu:
                    joined = g
                    break
            if joined is None:
                joined = next_gid[0]
                next_gid[0] += 1
                members_of[joined] = []
            group_of[n.key] = joined
            members_of[joined].append(n)
            ag = ag | {joined}
        all_groups[n.key] = ag
        via_unclaimed[n.key] = vu

    groups = {g: v for g, v in members_of.items()
              if len(v) >= prop.min_subgraph_size()}
    if not groups:
        return sym
    node_group = {n.key: g for g, v in groups.items() for n in v}

    # rebuild the graph, replacing each group with fused node(s): one
    # registered op per consumed (member, output_index) pair, all sharing
    # one memoized fused callable so the sub-DAG executes once per
    # distinct input set
    by_edge: dict = {}   # (key, output_index) -> rebuilt node
    canon_new: dict = {}  # key -> canonical rebuilt node (unfused path)
    group_nodes: dict = {}

    def convert(node):
        edge = (node.key, node.output_index)
        if edge in by_edge:
            return by_edge[edge]
        gid = node_group.get(node.key)
        if gid is None:
            canon = canon_new.get(node.key)
            if canon is None:
                new_inputs = [convert(i) for i in node.inputs]
                canon = sym_mod._SymNode(node.op_name, node.name, new_inputs,
                                         node.kwargs, node.attrs,
                                         node.num_outputs, 0)
                canon_new[node.key] = canon
                by_edge[(node.key, 0)] = canon
            nn = canon.clone_for_output(node.output_index)
            by_edge[edge] = nn
            return nn
        if gid not in group_nodes:
            members = groups[gid]
            member_keys = {m.key for m in members}
            ext, seen = [], set()
            for m in members:
                for i in m.inputs:
                    ie = (i.key, i.output_index)
                    if i.key not in member_keys and ie not in seen:
                        seen.add(ie)
                        ext.append(i)
            consumed_outside = set()   # (member key, output_index)
            for n2 in order:
                if n2.key in member_keys:
                    continue
                for i in n2.inputs:
                    if i.key in member_keys:
                        consumed_outside.add((i.key, i.output_index))
            for h in sym._head_entries():
                if h.key in member_keys:
                    consumed_outside.add((h.key, h.output_index))
            pos = {n.key: i for i, n in enumerate(order)}
            outs = sorted(consumed_outside, key=lambda e: (pos[e[0]], e[1]))

            fused_fn = prop.wrap_callable(
                _make_fused_callable(members, ext, outs))
            memo = {"args": None, "out": None}

            def run_all(args):
                prev = memo["args"]
                if prev is not None and len(prev) == len(args) and \
                        all(a is b for a, b in zip(prev, args)):
                    return memo["out"]
                out = fused_fn(*args)
                if not isinstance(out, tuple):
                    out = (out,)
                memo["args"] = args
                memo["out"] = out
                return out

            _FUSED_UID[0] += 1
            uid = _FUSED_UID[0]
            new_inputs = [convert(i) for i in ext]
            attrs = {"__subgraph__": prop.name,
                     "__n_ops__": str(len(members))}
            picks = {}
            for k, oe in enumerate(outs):
                op_name = f"_subgraph_{prop.name}_{uid}_out{k}"

                def out_fn(*args, _k=k):
                    return run_all(args)[_k]

                register(op_name)(out_fn)
                picks[oe] = sym_mod._SymNode(op_name, op_name,
                                             new_inputs, {}, attrs)
            group_nodes[gid] = picks
        picks = group_nodes[gid]
        by_edge[edge] = picks[edge]
        return picks[edge]

    new_heads = [convert(h) for h in sym._head_entries()]
    return sym_mod.Symbol(new_heads)


def _make_fused_callable(members, ext_inputs, outs):
    """One jit-compiled callable over the member sub-DAG.

    ``outs`` is a list of (member key, output_index) pairs — each fused
    output selects the right element of a multi-output member's tuple
    result (reference NodeEntry.index semantics).
    """
    from .ops.registry import get_op

    member_keys = {m.key for m in members}
    ext_pos = {(e.key, e.output_index): i for i, e in enumerate(ext_inputs)}
    # snapshot the sub-DAG structure (node → op + input wiring)
    plan = []
    for m in members:
        srcs = []
        for i in m.inputs:
            if i.key in member_keys:
                srcs.append(("m", i.key, i.output_index))
            else:
                srcs.append(("e", ext_pos[(i.key, i.output_index)], 0))
        plan.append((m.key, get_op(m.op_name), m.kwargs, srcs))

    @jax.jit  # mxlint: disable=MX-DONATE001(args are live NDArray chunk values the caller reads after the fused subgraph executes)
    def fused(*args):
        vals: dict = {}
        for mid, op, kwargs, srcs in plan:
            ins = []
            for kind, key, oidx in srcs:
                if kind == "e":
                    ins.append(args[key])
                else:
                    v = vals[key]
                    ins.append(v[oidx] if isinstance(v, tuple) else v)
            vals[mid] = op.fn(*ins, **kwargs)
        result = []
        for okey, oidx in outs:
            v = vals[okey]
            result.append(v[oidx] if isinstance(v, tuple) else v)
        return result[0] if len(result) == 1 else tuple(result)

    return fused
