"""Subgraph backend API (reference src/operator/subgraph/
subgraph_property.h:86-252, build_subgraph.cc, MXNET_SUBGRAPH_BACKEND).

Extension point parity: a backend registers a ``SubgraphProperty`` whose
selector claims ops; ``partition()`` greedily grows connected regions of
claimed nodes and replaces each with a single fused node executing the
sub-DAG through one ``jax.jit`` callable. The built-in ``"XLA"`` backend
claims every op — the whole-graph → one-XLA-program compile that
``simple_bind`` also performs, exposed through the same plugin surface
the reference uses for MKLDNN/TensorRT backends.
"""
from __future__ import annotations

import os
import threading

import jax

__all__ = ["SubgraphSelector", "SubgraphProperty", "register_backend",
           "get_backend", "list_backends", "partition",
           "default_backend_from_env"]

_BACKENDS: dict = {}
_lock = threading.Lock()


class SubgraphSelector:
    """Node-claiming policy (subgraph_property.h SubgraphSelector)."""

    def is_op_supported(self, node) -> bool:  # node: symbol._SymNode
        return False


class SubgraphProperty:
    """Backend description (subgraph_property.h SubgraphProperty)."""

    name = "base"

    def create_selector(self) -> SubgraphSelector:
        return SubgraphSelector()

    def min_subgraph_size(self) -> int:
        return 2

    # hook: backends may post-process the fused callable
    def wrap_callable(self, fn):
        return fn


def register_backend(prop: "SubgraphProperty | type"):
    """MXNET_REGISTER_SUBGRAPH_PROPERTY analog."""
    if isinstance(prop, type):
        prop = prop()
    with _lock:
        _BACKENDS[prop.name] = prop
    return prop


def get_backend(name: str) -> SubgraphProperty:
    try:
        return _BACKENDS[name]
    except KeyError:
        raise KeyError(
            f"subgraph backend {name!r} not registered "
            f"(have: {sorted(_BACKENDS)})") from None


def list_backends():
    return sorted(_BACKENDS)


def default_backend_from_env():
    """MXNET_SUBGRAPH_BACKEND env knob (reference
    docs faq/perf.md:61 / build_subgraph.cc)."""
    return os.environ.get("MXNET_SUBGRAPH_BACKEND", "")


class _AllSelector(SubgraphSelector):
    def is_op_supported(self, node):
        return True


class XLAProperty(SubgraphProperty):
    """Swallow the maximal subgraph into one XLA program (SURVEY.md §2.1
    subgraph row: the natural home of whole-graph compilation)."""

    name = "XLA"

    def create_selector(self):
        return _AllSelector()

    def min_subgraph_size(self):
        return 1


register_backend(XLAProperty)


_FUSED_UID = [0]


def partition(sym, backend_name):
    """Partition a Symbol under a backend: contiguous regions of claimed
    ops become fused nodes (reference build_subgraph.cc BuildSubgraph).

    Returns a new Symbol whose fused regions execute as single jitted
    callables through per-partition registered ops. Grouping is
    cycle-safe: a claimed node only joins an input's group when that
    group is not also reachable through an unclaimed path (otherwise the
    fused node would depend on an external input that depends on it).
    """
    from . import symbol as sym_mod
    from .ops.registry import register

    prop = get_backend(backend_name)
    selector = prop.create_selector()
    order = sym._topo_order()

    claimed = {id(n) for n in order
               if n.op_name is not None and selector.is_op_supported(n)}

    # group assignment in topo order with cycle check:
    #   all_groups[v]    = groups reachable from v (any path)
    #   via_unclaimed[v] = groups reachable only via ≥1 unclaimed node
    group_of: dict = {}
    members_of: dict = {}
    all_groups: dict = {}
    via_unclaimed: dict = {}
    next_gid = [0]
    for n in order:
        ag, vu = set(), set()
        for i in n.inputs:
            ag |= all_groups.get(id(i), set())
            if id(i) in claimed:
                vu |= via_unclaimed.get(id(i), set())
            else:
                # path through an unclaimed node: everything reachable
                # from it becomes forbidden for joining
                vu |= all_groups.get(id(i), set())
                vu |= via_unclaimed.get(id(i), set())
        if id(n) in claimed:
            joined = None
            for i in n.inputs:
                g = group_of.get(id(i))
                if g is not None and g not in vu:
                    joined = g
                    break
            if joined is None:
                joined = next_gid[0]
                next_gid[0] += 1
                members_of[joined] = []
            group_of[id(n)] = joined
            members_of[joined].append(n)
            ag = ag | {joined}
        all_groups[id(n)] = ag
        via_unclaimed[id(n)] = vu

    groups = {g: v for g, v in members_of.items()
              if len(v) >= prop.min_subgraph_size()}
    if not groups:
        return sym
    node_group = {id(n): g for g, v in groups.items() for n in v}

    # rebuild the graph, replacing each group with fused node(s): one
    # registered op per consumed output, all sharing one memoized fused
    # callable so the sub-DAG executes once per distinct input set
    by_id: dict = {}
    group_nodes: dict = {}

    def convert(node):
        if id(node) in by_id:
            return by_id[id(node)]
        gid = node_group.get(id(node))
        if gid is None:
            new_inputs = [convert(i) for i in node.inputs]
            nn = sym_mod._SymNode(node.op_name, node.name, new_inputs,
                                  node.kwargs, node.attrs,
                                  node.num_outputs, node.output_index)
            by_id[id(node)] = nn
            return nn
        if gid not in group_nodes:
            members = groups[gid]
            member_ids = {id(m) for m in members}
            ext, seen = [], set()
            for m in members:
                for i in m.inputs:
                    if id(i) not in member_ids and id(i) not in seen:
                        seen.add(id(i))
                        ext.append(i)
            consumed_outside = set()
            for n2 in order:
                if id(n2) in member_ids:
                    continue
                for i in n2.inputs:
                    if id(i) in member_ids:
                        consumed_outside.add(id(i))
            for h in sym._nodes:
                if id(h) in member_ids:
                    consumed_outside.add(id(h))
            outs = [m for m in members if id(m) in consumed_outside]

            fused_fn = prop.wrap_callable(
                _make_fused_callable(members, ext, outs))
            memo = {"args": None, "out": None}

            def run_all(args):
                prev = memo["args"]
                if prev is not None and len(prev) == len(args) and \
                        all(a is b for a, b in zip(prev, args)):
                    return memo["out"]
                out = fused_fn(*args)
                if not isinstance(out, tuple):
                    out = (out,)
                memo["args"] = args
                memo["out"] = out
                return out

            _FUSED_UID[0] += 1
            uid = _FUSED_UID[0]
            new_inputs = [convert(i) for i in ext]
            attrs = {"__subgraph__": prop.name,
                     "__n_ops__": str(len(members))}
            picks = {}
            for k, o in enumerate(outs):
                op_name = f"_subgraph_{prop.name}_{uid}_out{k}"

                def out_fn(*args, _k=k):
                    return run_all(args)[_k]

                register(op_name)(out_fn)
                picks[id(o)] = sym_mod._SymNode(op_name, op_name,
                                                new_inputs, {}, attrs)
            group_nodes[gid] = picks
        picks = group_nodes[gid]
        by_id[id(node)] = picks[id(node)]
        return picks[id(node)]

    new_heads = [convert(h) for h in sym._nodes]
    return sym_mod.Symbol(new_heads)


def _make_fused_callable(members, ext_inputs, outs):
    """One jit-compiled callable over the member sub-DAG."""
    from .ops.registry import get_op

    member_ids = {id(m) for m in members}
    ext_pos = {id(e): i for i, e in enumerate(ext_inputs)}
    out_ids = [id(o) for o in outs]
    # snapshot the sub-DAG structure (node → op + input wiring)
    plan = []
    for m in members:
        srcs = []
        for i in m.inputs:
            if id(i) in member_ids:
                srcs.append(("m", id(i), i.output_index))
            else:
                srcs.append(("e", ext_pos[id(i)], 0))
        plan.append((id(m), get_op(m.op_name), m.kwargs, srcs))

    @jax.jit
    def fused(*args):
        vals: dict = {}
        for mid, op, kwargs, srcs in plan:
            ins = []
            for kind, key, oidx in srcs:
                if kind == "e":
                    ins.append(args[key])
                else:
                    v = vals[key]
                    ins.append(v[oidx] if isinstance(v, tuple) else v)
            vals[mid] = op.fn(*ins, **kwargs)
        result = []
        for oid in out_ids:
            v = vals[oid]
            result.append(v if not isinstance(v, tuple) else v[0])
        return result[0] if len(result) == 1 else tuple(result)

    return fused
