"""Image IO and augmentation (reference python/mxnet/image/).

The reference decodes via OpenCV inside the C++ iterator
(src/io/image_aug_default.cc).  Here decode uses cv2 if present, else
Pillow, else raw numpy codecs — and augmenters are pure-numpy host-side
transforms (TPU does not help with JPEG decode; keeping host decode off
the device path mirrors the reference's design).
"""
from __future__ import annotations

import io as _io
import random as _pyrandom

import numpy as onp

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["imread", "imdecode", "imencode", "imresize", "resize_short",
           "center_crop", "random_crop", "fixed_crop", "color_normalize",
           "CreateAugmenter", "Augmenter", "ImageIter",
           "DetAugmenter", "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetBorderAug", "DetColorNormalizeAug", "CreateDetAugmenter",
           "ImageDetIter"]


def _decode_bytes(buf: bytes, flag=1):
    try:
        import cv2
        arr = onp.frombuffer(buf, dtype=onp.uint8)
        img = cv2.imdecode(arr, 1 if flag else 0)
        if img is None:
            raise ValueError("cv2 failed to decode image")
        if flag:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        return img
    except ImportError:
        pass
    try:
        from PIL import Image
        img = Image.open(_io.BytesIO(buf))
        img = img.convert("RGB" if flag else "L")
        arr = onp.asarray(img)
        if not flag:
            arr = arr[..., None]
        return arr
    except ImportError as e:
        raise RuntimeError("no image decoder available (cv2/PIL)") from e


def imdecode_np(buf, flag=1):
    return _decode_bytes(bytes(buf), flag)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    return nd.array(_decode_bytes(bytes(buf), flag))


def imencode(img, fmt=".jpg", quality=95) -> bytes:
    if isinstance(img, NDArray):
        img = img.asnumpy()
    img = onp.asarray(img, dtype=onp.uint8)
    try:
        import cv2
        ok, buf = cv2.imencode(fmt, cv2.cvtColor(img, cv2.COLOR_RGB2BGR),
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        if not ok:
            raise ValueError("cv2 encode failed")
        return bytes(buf)
    except ImportError:
        pass
    from PIL import Image
    bio = _io.BytesIO()
    Image.fromarray(img.squeeze() if img.shape[-1] == 1 else img).save(
        bio, format="JPEG" if fmt in (".jpg", ".jpeg") else "PNG",
        quality=quality)
    return bio.getvalue()


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    import jax.image
    data = src.data if isinstance(src, NDArray) else onp.asarray(src)
    out = jax.image.resize(data.astype("float32"), (h, w, data.shape[2]),
                           method="bilinear")
    return NDArray(out.astype(str(src.dtype) if isinstance(src, NDArray)
                              else data.dtype.name))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != tuple(size):
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") - nd.array(onp.asarray(mean, "float32"))
    if std is not None:
        src = src / nd.array(onp.asarray(std, "float32"))
    return src


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return NDArray(src.data[:, ::-1], ctx=src.ctx)
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter chain (reference image.py CreateAugmenter)."""
    auglist: list[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(
            mean if mean is not None else [0, 0, 0],
            std if std is not None else [1, 1, 1]))
    return auglist


class ImageIter:
    """Image iterator over RecordIO or file list (reference image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, **kwargs):
        from . import recordio as rio
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter((3,) + self.data_shape[1:])
        self._records = []
        if path_imgrec:
            idx_path = path_imgrec[:path_imgrec.rfind(".")] + ".idx"
            rec = rio.MXIndexedRecordIO(idx_path, path_imgrec, "r") \
                if __import__("os").path.exists(idx_path) \
                else rio.MXRecordIO(path_imgrec, "r")
            if hasattr(rec, "keys") and rec.keys:
                for k in rec.keys:
                    self._records.append(rec.read_idx(k))
            else:
                while True:
                    buf = rec.read()
                    if buf is None:
                        break
                    self._records.append(buf)
        self._order = list(range(len(self._records)))
        self._shuffle = shuffle
        self._cursor = 0
        self.reset()

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            _pyrandom.shuffle(self._order)

    def __iter__(self):
        return self

    def __next__(self):
        from . import recordio as rio
        from .io import DataBatch
        if self._cursor + self.batch_size > len(self._records):
            raise StopIteration
        imgs, labels = [], []
        for i in range(self.batch_size):
            buf = self._records[self._order[self._cursor + i]]
            header, img_buf = rio.unpack(buf)
            img = imdecode(img_buf)
            for aug in self.auglist:
                img = aug(img)
            imgs.append(img.transpose((2, 0, 1)).astype("float32"))
            labels.append(header.label)
        self._cursor += self.batch_size
        data = nd.stack(*imgs, axis=0)
        label = nd.array(onp.asarray(labels, "float32"))
        return DataBatch(data=[data], label=[label])

    next = __next__


# ---------------------------------------------------------------------------
# detection pipeline (reference python/mxnet/image/detection.py):
# bbox-aware augmenters + ImageDetIter.  Labels are rows of
# [class, xmin, ymin, xmax, ymax] with coordinates normalized to [0, 1]
# (the reference's object format after its header is stripped).
# ---------------------------------------------------------------------------

class DetAugmenter:
    """Augmenter transforming (image, label) together."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p (reference
    detection.py DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if onp.random.rand() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            xmin = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - xmin
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough box overlap (reference
    DetRandomCropAug: min_object_covered / area-range sampling,
    simplified to bounded retries)."""

    def __init__(self, min_object_covered=0.5, min_crop_size=0.5,
                 max_attempts=25):
        self.min_object_covered = min_object_covered
        self.min_crop_size = min_crop_size
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            cw = onp.random.uniform(self.min_crop_size, 1.0)
            ch = onp.random.uniform(self.min_crop_size, 1.0)
            cx = onp.random.uniform(0, 1.0 - cw)
            cy = onp.random.uniform(0, 1.0 - ch)
            new = self._project(label, cx, cy, cw, ch)
            if new is not None:
                x0, y0 = int(cx * w), int(cy * h)
                x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
                return src[y0:y1, x0:x1], new
        return src, label

    def _project(self, label, cx, cy, cw, ch):
        """Boxes re-expressed in crop coordinates; None if coverage of
        any kept object falls below min_object_covered."""
        out = []
        for row in label:
            cls, xmin, ymin, xmax, ymax = row[:5]
            ix0, iy0 = max(xmin, cx), max(ymin, cy)
            ix1, iy1 = min(xmax, cx + cw), min(ymax, cy + ch)
            inter = max(0.0, ix1 - ix0) * max(0.0, iy1 - iy0)
            area = (xmax - xmin) * (ymax - ymin)
            if area <= 0 or inter / area < 1e-6:
                continue                      # object fully outside: drop
            if inter / area < self.min_object_covered:
                return None                   # partially cut: reject crop
            out.append([cls,
                        max(0.0, (xmin - cx) / cw),
                        max(0.0, (ymin - cy) / ch),
                        min(1.0, (xmax - cx) / cw),
                        min(1.0, (ymax - cy) / ch)])
        if not out:
            return None
        return onp.asarray(out, onp.float32)


class DetBorderAug(DetAugmenter):
    """Pad to square with a fill value, boxes re-normalized (reference
    DetRandomPadAug, deterministic variant)."""

    def __init__(self, fill=127):
        self.fill = fill

    def __call__(self, src, label):
        h, w = src.shape[:2]
        s = max(h, w)
        out = onp.full((s, s) + src.shape[2:], self.fill, src.dtype)
        y0, x0 = (s - h) // 2, (s - w) // 2
        out[y0:y0 + h, x0:x0 + w] = src
        label = label.copy()
        label[:, [1, 3]] = (label[:, [1, 3]] * w + x0) / s
        label[:, [2, 4]] = (label[:, [2, 4]] * h + y0) / s
        return out, label


class DetColorNormalizeAug(DetAugmenter):
    """Color normalization; labels pass through (reference detection.py
    wraps the classification augmenter the same way)."""

    def __init__(self, mean, std=None):
        self.mean = onp.asarray(mean, onp.float32)
        self.std = onp.asarray(std, onp.float32) if std is not None else None

    def __call__(self, src, label):
        out = onp.asarray(src, onp.float32) - self.mean
        if self.std is not None:
            out = out / self.std
        return out, label


def CreateDetAugmenter(data_shape, rand_crop=0, rand_mirror=False,
                       rand_pad=0, mean=None, std=None):
    """Standard detection augmentation chain (reference
    detection.py CreateDetAugmenter)."""
    augs: list = []
    if rand_pad:
        augs.append(DetBorderAug())
    if rand_crop:
        augs.append(DetRandomCropAug())
    if rand_mirror:
        augs.append(DetHorizontalFlipAug())
    if mean is not None:
        augs.append(DetColorNormalizeAug(mean, std))
    return augs


class ImageDetIter:
    """Detection batches with padded multi-object labels (reference
    image/detection.py ImageDetIter).

    imglist: list of (HWC uint8/float array, label rows (N, 5)).  Emits
    data (B, C, H, W) float32 and label (B, max_objs, 5) padded with -1
    rows — the contract MultiBoxTarget consumes (ops/contrib_ops.py).
    """

    def __init__(self, batch_size, data_shape, imglist, augmenters=None,
                 shuffle=False, label_shape=None):
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self._items = list(imglist)
        self._augs = augmenters or []
        self._shuffle = shuffle
        self._order = list(range(len(self._items)))
        self._cursor = 0
        # fixed label arity across batches (reference label_shape): a
        # per-batch max would change shapes batch-to-batch and force XLA
        # recompiles in every consumer
        if label_shape is not None:
            self._max_objs = int(label_shape[0])
        else:
            self._max_objs = max(
                (onp.asarray(l).reshape(-1, 5).shape[0]
                 for _, l in self._items), default=1)
        self.reset()

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            onp.random.shuffle(self._order)

    def __iter__(self):
        # no implicit reset: DataIter semantics (reset() starts an epoch)
        return self

    def __next__(self):
        from .io import DataBatch
        from .ndarray import NDArray
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        idxs = [self._order[(self._cursor + i) % n]
                for i in range(self.batch_size)]
        pad = max(0, self._cursor + self.batch_size - n)
        self._cursor += self.batch_size
        c, h, w = self.data_shape
        datas, labels = [], []
        for i in idxs:
            img, lab = self._items[i]
            img = onp.asarray(img)
            lab = onp.asarray(lab, onp.float32).reshape(-1, 5)
            for aug in self._augs:
                img, lab = aug(img, lab)
            img = imresize(img, w, h).asnumpy()
            datas.append(img.astype(onp.float32).transpose(2, 0, 1))
            labels.append(lab)
        lab_out = onp.full((self.batch_size, self._max_objs, 5), -1.0,
                           onp.float32)
        for bi, l in enumerate(labels):
            k = min(len(l), self._max_objs)
            lab_out[bi, :k] = l[:k]
        return DataBatch(data=[NDArray(onp.stack(datas))],
                         label=[NDArray(lab_out)], pad=pad)

    next = __next__
