"""Unified Executor: the single compile choke point, and the cold-start
caches stacked on top of it.

The framework has four separately-grown compile surfaces — the Gluon
``CachedOp`` (gluon/block.py), bulked eager segments (ops/bulking.py),
the fused train step (fuse.py) and the deploy ``Predictor`` (deploy.py).
Each used to wire the same three cross-cutting concerns by hand: the
recompile sentinel's ``instrument``, graphlint's ``check_traced`` and
memlint's ``check_memory``, plus its own ad-hoc trace-cache dict.  This
module is the one place all of that lives now:

* :class:`Executor` — wraps the python function a surface hands to
  ``jax.jit``: sentinel instrumentation, donation/sharding options, and
  the jit object itself, with a ``compile_count`` probe shared by the
  serving metrics.  Creating an Executor is also the point where the
  persistent compilation cache is switched on (below), so *every*
  compile surface rides it without per-surface wiring.
* :func:`run_analyses` — THE build-time graphlint/memlint wiring.  A
  surface states its contract (donation, allowed-undonated positions,
  ignored rules); the gating on ``MXNET_GRAPH_LINT`` /
  ``MXNET_GRAPH_MEMLINT`` and the calls into the analysis passes happen
  here, once.
* :class:`TraceCache` — the shared trace-cache shape (lock, hit/miss
  counters, stats) behind ``CachedOp._cache`` and the bulking segment
  cache, so "did a steady-state loop retrace" is answerable uniformly.

Cold-start persistence (ROADMAP item 2 — replica cold-start from
minutes to seconds) stacks two layers on this choke point:

* **Persistent XLA compilation cache** — ``MXNET_COMPILE_CACHE_DIR``
  points JAX's compilation cache at a directory
  (``jax_compilation_cache_dir``); a second process on the same host
  (a serving replica spawn, an elastic worker join, a rolling reload)
  skips XLA compilation for every graph the first process built.
  Enabled at ONE init point (:func:`ensure_compile_cache`), called by
  every Executor construction, with min-entry-size / min-compile-time
  thresholds so tiny graphs don't churn the directory.
* **AOT-serialized executables** — :func:`serialize_executable` /
  :func:`deserialize_executable` wrap
  ``jax.experimental.serialize_executable`` with a versioned
  compatibility envelope (jax/jaxlib versions + platform), so deploy
  artifacts can ship per-bucket *compiled* executables and a loader can
  refuse — loudly, with a recompile fallback — a blob built by a
  different toolchain instead of crashing inside an unpickler.

Observability: a ``cold_start`` profiler stats provider reports time
from process start to first executable build, per-site build counts,
the persistent-cache configuration, and AOT load hits/failures.
"""
from __future__ import annotations

import json
import threading
import time

import jax

from .base import get_env
from .locks import named_lock

__all__ = ["Executor", "TraceCache", "run_analyses", "lint_active",
           "memlint_active", "ensure_compile_cache", "compile_cache_dir",
           "serialize_executable", "deserialize_executable", "aot_compat",
           "AOTCompatError", "record_aot_load", "process_uptime_ms",
           "stats", "reset_stats"]

_PROCESS_T0 = time.monotonic()

_lock = named_lock("executor.state")
_state = {
    "cache_init_done": False,
    "cache_dir": None,
    "first_build_ms": None,        # process start -> first Executor build
    "aot_loads": 0,
    "aot_load_failures": 0,
    "analyses": 0,
}
_sites: dict[str, dict] = {}       # site -> {"executors": n, "built_ms": t}
_provider_registered = False


class AOTCompatError(RuntimeError):
    """An AOT-serialized executable was built by an incompatible
    toolchain (jax/jaxlib version or platform mismatch) or the blob is
    malformed.  Loaders catch this and fall back to recompilation."""


# ---------------------------------------------------------------------------
# persistent compilation cache — the one shared init point
# ---------------------------------------------------------------------------

def compile_cache_dir():
    """The configured persistent-cache directory, or None (off)."""
    d = get_env("MXNET_COMPILE_CACHE_DIR", "")
    return d or None


def ensure_compile_cache():
    """Switch on JAX's persistent compilation cache if
    ``MXNET_COMPILE_CACHE_DIR`` is set.  Idempotent and cheap after the
    first call; every Executor construction routes through here, so any
    process that compiles anything gets the cache without per-surface
    wiring.  Returns the cache dir or None.

    Thresholds (both default to "cache everything" because cold start
    is what the cache exists to kill; raise them on hosts where the
    cache directory competes with real data):

    * ``MXNET_COMPILE_CACHE_MIN_ENTRY_BYTES`` — skip persisting
      executables smaller than this.
    * ``MXNET_COMPILE_CACHE_MIN_COMPILE_SECS`` — skip persisting
      compilations faster than this.
    """
    with _lock:
        if _state["cache_init_done"]:
            return _state["cache_dir"]
        _state["cache_init_done"] = True
        d = compile_cache_dir()
        if d is None:
            return None
        try:
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              get_env("MXNET_COMPILE_CACHE_MIN_ENTRY_BYTES",
                                      0, int))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              get_env("MXNET_COMPILE_CACHE_MIN_COMPILE_SECS",
                                      0.0, float))
            # jax's cache module latches its enabled/disabled state at
            # the first compile; anything compiled before this init
            # (eager op dispatch during import) would leave it stuck
            # disabled — drop the latch so the new dir takes effect
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception as e:  # mxlint: allow-broad-except(an unsupported jax config key must degrade to cold compiles, never break model building)
            import warnings
            # roll back any config that DID apply before the failure:
            # the reported state (off) must match reality, not leave a
            # half-enabled cache behind the "cold compiles" warning
            try:
                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:  # mxlint: allow-broad-except(rollback of a possibly-never-applied key; nothing further to do on failure)
                pass
            warnings.warn(
                f"persistent compilation cache unavailable ({e}); "
                "compiles will be cold in every process")
            _state["cache_dir"] = None
            return None
        _state["cache_dir"] = d
        return d


def _reset_compile_cache_for_tests():
    """Allow a test to re-run ensure_compile_cache with a fresh env."""
    with _lock:
        _state["cache_init_done"] = False
        _state["cache_dir"] = None


# ---------------------------------------------------------------------------
# the choke point
# ---------------------------------------------------------------------------

def _ensure_provider():
    global _provider_registered
    if _provider_registered:
        return
    _provider_registered = True
    from . import profiler
    profiler.register_stats_provider("cold_start", stats)


class Executor:
    """One jitted entry point built through the unified choke point.

    ``Executor(fn, site)`` is the replacement for a bare
    ``jax.jit(_recompile.instrument(fn, site), ...)``: persistent-cache
    init, sentinel instrumentation and the jit options live here; the
    surface keeps only its calling convention.  ``executor.jfn`` is the
    jitted callable; :attr:`compile_count` probes the jit executable
    cache (the serving "must flatline after warmup" counter).
    """

    __slots__ = ("site", "fn", "jfn", "donate_argnums", "_built_at")

    def __init__(self, fn, site, donate_argnums=(), in_shardings=None,
                 static_argnums=None, static_argnames=None,
                 instrument=True):
        from .analysis import recompile as _recompile
        ensure_compile_cache()
        _ensure_provider()
        self.site = site
        self.fn = fn
        self.donate_argnums = tuple(donate_argnums)
        kwargs = {}
        if self.donate_argnums:
            kwargs["donate_argnums"] = self.donate_argnums
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
        if static_argnums is not None:
            kwargs["static_argnums"] = static_argnums
        if static_argnames is not None:
            kwargs["static_argnames"] = static_argnames
        # instrument=False is for surfaces that detect their own cache
        # misses and report a richer compile signature themselves (the
        # bulking trace cache) via recompile.record_compile
        wrapped = _recompile.instrument(fn, site) if instrument else fn
        self.jfn = jax.jit(wrapped, **kwargs)  # mxlint: disable=MX-DONATE001(donation is threaded via kwargs — every Executor caller states its donate_argnums contract at construction, and () means caller-held inputs)
        # an Executor built while a request trace is active means that
        # request is paying a build the warm path would not — stamp it
        # on the trace (the XLA compile itself lands inside whatever
        # span is timing the call; this event names the site) AND on
        # the always-on flight ring, where a postmortem can see a
        # compile burst precede an incident even with tracing off
        from . import trace as _trace
        _trace.add_event("executor.created", site=site)
        from . import flightrec as _flightrec
        _flightrec.record(_flightrec.COMPILE, "executor.created",
                          site=site)
        self._built_at = time.monotonic()
        with _lock:
            if _state["first_build_ms"] is None:
                _state["first_build_ms"] = round(
                    (self._built_at - _PROCESS_T0) * 1000.0, 3)
            st = _sites.setdefault(site, {"executors": 0})
            st["executors"] += 1
            st["built_ms_after_start"] = round(
                (self._built_at - _PROCESS_T0) * 1000.0, 3)

    def __call__(self, *args, **kwargs):
        return self.jfn(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self.jfn.lower(*args, **kwargs)

    @property
    def compile_count(self):
        """Distinct executables this entry point compiled (jit cache
        probe; AOT-loaded executables never appear here — that is the
        point)."""
        try:
            return int(self.jfn._cache_size())
        except Exception:  # mxlint: allow-broad-except(best-effort probe of a private jax internal; a degraded count beats failing a metrics scrape)
            return 0

    def analyze(self, args, graphlint=None, memlint=None,
                shardlint=None):
        """Run the build-time analyses over the *uninstrumented* fn with
        this executor's donation contract pre-applied (a surface can
        still override per-call)."""
        gl = dict(graphlint) if graphlint is not None else None
        ml = dict(memlint) if memlint is not None else None
        sl = dict(shardlint) if shardlint is not None else None
        if gl is not None:
            gl.setdefault("donate_argnums", self.donate_argnums)
        if ml is not None:
            ml.setdefault("donate_argnums", self.donate_argnums)
        if sl is not None:
            sl.setdefault("donate_argnums", self.donate_argnums)
        return run_analyses(self.fn, args, name=self.site,
                            graphlint=gl, memlint=ml, shardlint=sl)


def lint_active():
    """Whether build-time graphlint is on (``MXNET_GRAPH_LINT`` /
    ``graphlint.set_lint_mode``) — for frontends that gate expensive
    argument prep or manage an analyzed-once latch."""
    from .analysis import graphlint
    return graphlint.lint_mode() is not None


def memlint_active():
    """Whether build-time memlint is on (``MXNET_GRAPH_MEMLINT`` /
    ``memlint.set_mem_mode``)."""
    from .analysis import memlint
    return memlint.mem_mode() is not None


def shardlint_active():
    """Whether build-time shardlint is on (``MXNET_GRAPH_SHARDLINT`` /
    ``shardlint.set_shard_mode``)."""
    from .analysis import shardlint
    return shardlint.shard_mode() is not None


def latch_train_analyses(executor, args, lint_done, memlint_done):
    """One-shot build-time graphlint/memlint for a donated train
    program (the fused step and the chunked loop share this exact
    discipline): each latch sets only once its mode is on, so
    enabling a mode after step 1 still analyzes; GL-DEAD001 is
    ignored by documented scope limit (AD transposition leaves dead
    primal eqns in every value_and_grad trace — straight-line or
    scanned); donation is REQUIRED (the train-state carry contracts
    to donate).  Returns the updated ``(lint_done, memlint_done)``."""
    do_lint = not lint_done and lint_active()
    do_mem = not memlint_done and memlint_active()
    if do_lint or do_mem:
        from .analysis import graphlint as _graphlint
        executor.analyze(
            args,
            graphlint=dict(
                check_donation=True,
                config=_graphlint.Config(ignore={"GL-DEAD001"}),
            ) if do_lint else None,
            memlint=dict(require_donation=True) if do_mem else None)
    return lint_done or do_lint, memlint_done or do_mem


def run_analyses(fn, args, name, graphlint=None, memlint=None,
                 shardlint=None):
    """THE graphlint/memlint/shardlint build-time wiring (previously
    copied at every compile surface).  ``graphlint``/``memlint``/
    ``shardlint`` are kwarg dicts for
    :func:`analysis.graphlint.check_traced` /
    :func:`analysis.memlint.check_memory` /
    :func:`analysis.shardlint.check_sharding` — pass ``None`` to skip a
    pass entirely, ``{}`` for the defaults.  Inert (three cached env
    reads) unless the respective mode is on.  Returns
    ``(findings, mem_report)``; the shard report is recorded in the
    ``shardlint`` profiler provider's per-site stats.
    """
    findings = rep = None
    if graphlint is not None:
        from .analysis import graphlint as _graphlint
        if _graphlint.lint_mode() is not None:
            findings = _graphlint.check_traced(fn, args, name=name,
                                               **graphlint)
    if memlint is not None:
        from .analysis import memlint as _memlint
        if _memlint.mem_mode() is not None:
            rep = _memlint.check_memory(fn, args, name=name, **memlint)
    srep = None
    if shardlint is not None:
        from .analysis import shardlint as _shardlint
        if _shardlint.shard_mode() is not None:
            srep = _shardlint.check_sharding(fn, args, name=name,
                                             **shardlint)
    if findings is not None or rep is not None or srep is not None:
        with _lock:
            _state["analyses"] += 1
    return findings, rep


class TraceCache:
    """Keyed executable cache with hit/miss accounting — the shared
    shape behind CachedOp's per-signature cache and the bulking segment
    cache.  Keys are the caller's business (op sequence / Block
    signature / bucket + shapes/dtypes/statics); this class owns the
    lock and the counters so cache behavior is observable uniformly."""

    __slots__ = ("name", "_d", "_lock", "hits", "misses")

    def __init__(self, name):
        self.name = name
        self._d: dict = {}
        self._lock = named_lock("executor.cache")
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            entry = self._d.get(key)
            if entry is None:
                self.misses += 1
            else:
                self.hits += 1
            return entry

    def put(self, key, value):
        with self._lock:
            self._d[key] = value
        return value

    def get_or_create(self, key, factory):
        """Atomic lookup-or-build: ``factory()`` runs under the cache
        lock, so two threads racing on one key can never build (and
        report to the sentinel) twice.  Returns ``(entry, hit)``.

        Build-vs-cache-hit is trace-visible: a hit adds an instant
        event to the active request span, a miss times ``factory()``
        as an ``executor.build`` span — the difference between "paid a
        compile" and "replayed an executable" for exactly the request
        that paid it (docs/observability.md)."""
        from . import trace as _trace
        with self._lock:
            entry = self._d.get(key)
            if entry is not None:
                self.hits += 1
                _trace.add_event("trace_cache.hit", cache=self.name)
                return entry, True
            self.misses += 1
            with _trace.span("executor.build", cache=self.name):
                entry = self._d[key] = factory()
            return entry, False

    def peek(self, key):
        """Lookup without touching the hit/miss counters (re-checks
        after a race, stats probes)."""
        with self._lock:
            return self._d.get(key)

    def clear(self):
        with self._lock:
            n = len(self._d)
            self._d.clear()
        return n

    def __len__(self):
        with self._lock:
            return len(self._d)

    def stats(self):
        with self._lock:
            return {"entries": len(self._d), "hits": self.hits,
                    "misses": self.misses}


# ---------------------------------------------------------------------------
# AOT executable serialization (versioned envelope over jax.experimental)
# ---------------------------------------------------------------------------

_AOT_MAGIC = b"MXTAOT1\n"


def aot_compat():
    """The compatibility claim stamped into (and checked against) every
    AOT blob: serialized executables are jax/jaxlib/platform-exact."""
    import jaxlib
    backend = jax.default_backend()
    return {"format": "mxtpu_aot_v1",
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": backend}


def serialize_executable(compiled):
    """Envelope + payload for a ``jax.stages.Compiled`` (from
    ``jax.jit(...).lower(...).compile()``).  The envelope is a JSON
    header checked BEFORE the pickle payload is touched — an
    incompatible or corrupted blob must be rejected by a version
    string comparison, not by whatever an unpickler does with garbage.
    """
    from jax.experimental.serialize_executable import serialize
    payload, in_tree, out_tree = serialize(compiled)
    header = dict(aot_compat())
    blob_header = json.dumps(header, sort_keys=True).encode()
    import pickle
    trees = pickle.dumps((in_tree, out_tree))
    parts = [_AOT_MAGIC,
             len(blob_header).to_bytes(8, "little"), blob_header,
             len(trees).to_bytes(8, "little"), trees,
             len(payload).to_bytes(8, "little"), payload]
    return b"".join(parts)


def deserialize_executable(blob, record=True):
    """Load an AOT blob back into a callable executable.

    Raises :class:`AOTCompatError` on any mismatch or corruption — the
    caller's contract is to catch it, warn loudly, and recompile.  The
    compat check runs before the pickle payload is deserialized.
    ``record=False`` keeps the load out of the ``cold_start``
    aot_loads/failure counters (export-time self-checks are
    validation, not cold-start cache traffic)."""
    try:
        if not blob.startswith(_AOT_MAGIC):
            raise AOTCompatError(
                "not an mxtpu AOT executable (bad magic); the artifact "
                "is corrupted or from an incompatible exporter")
        off = len(_AOT_MAGIC)

        def take(n):
            nonlocal off
            piece = blob[off:off + n]
            if len(piece) != n:
                raise AOTCompatError("truncated AOT executable blob")
            off += n
            return piece

        hlen = int.from_bytes(take(8), "little")
        header = json.loads(take(hlen).decode())
        want = aot_compat()
        mismatched = {k: (header.get(k), want[k]) for k in want
                      if header.get(k) != want[k]}
        if mismatched:
            raise AOTCompatError(
                "AOT executable was built by an incompatible toolchain: "
                + "; ".join(f"{k}: artifact={a!r} runtime={b!r}"
                            for k, (a, b) in sorted(mismatched.items()))
                + " — falling back to recompilation is required")
        import pickle
        tlen = int.from_bytes(take(8), "little")
        in_tree, out_tree = pickle.loads(take(tlen))
        plen = int.from_bytes(take(8), "little")
        payload = take(plen)
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        loaded = deserialize_and_load(payload, in_tree, out_tree)
        if record:
            record_aot_load(ok=True)
        return loaded
    except AOTCompatError:
        if record:
            record_aot_load(ok=False)
        raise
    except Exception as e:  # mxlint: allow-broad-except(any decode/unpickle failure of a foreign blob must surface as the typed compat error the fallback path catches)
        if record:
            record_aot_load(ok=False)
        raise AOTCompatError(
            f"AOT executable blob unusable ({type(e).__name__}: {e}); "
            "falling back to recompilation is required") from e


def record_aot_load(ok=True):
    """Count an AOT executable load (success/failure) for the
    ``cold_start`` stats provider and the serving gauges."""
    _ensure_provider()
    with _lock:
        _state["aot_loads" if ok else "aot_load_failures"] += 1


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------

def process_uptime_ms():
    return round((time.monotonic() - _PROCESS_T0) * 1000.0, 3)


def stats():
    """The ``cold_start`` profiler stats provider."""
    with _lock:
        # per-op eager sites (op:*) number in the hundreds — count them
        # but keep the detail table to the structural surfaces
        per_site = {k: dict(v) for k, v in _sites.items()
                    if not k.startswith("op:")}
        out = {
            "process_uptime_ms": process_uptime_ms(),
            "first_executor_build_ms": _state["first_build_ms"],
            "persistent_cache_dir": _state["cache_dir"],
            "aot_loads": _state["aot_loads"],
            "aot_load_failures": _state["aot_load_failures"],
            "analyses": _state["analyses"],
            "sites": len(_sites),
            "op_sites": sum(1 for k in _sites if k.startswith("op:")),
            "per_site": per_site,
        }
    return out


def reset_stats():
    """Drop per-site state (tests).  The persistent-cache init latch is
    deliberately kept — re-pointing a live process's cache dir is not a
    supported operation (use _reset_compile_cache_for_tests)."""
    with _lock:
        _sites.clear()
        _state["first_build_ms"] = None
        _state["aot_loads"] = 0
        _state["aot_load_failures"] = 0
        _state["analyses"] = 0
