"""Request-scoped distributed tracing: spans across router → replica →
batcher → device (docs/observability.md).

The stack has deep *aggregate* observability — Prometheus counters,
latency histograms, a dozen profiler stats providers — but none of it
answers "where did THIS slow request spend its time?".  This module is
the request-scoped layer: a pure-stdlib, monotonic-clock span recorder
with context propagation, near-zero off cost, and Chrome trace-event
export, threaded through every stage a request crosses:

* **Birth / adoption** — a trace is born at a front end (router or
  server) by a head-sampling decision (``MXNET_TRACE_SAMPLE``, default
  0 ⇒ the hot path pays one branch), or adopted from an
  ``X-MXNET-TRACE`` header (``traceid-spanid-sampled``).  The header's
  sampled flag is authoritative: an upstream "1" records even when
  local sampling is off; a garbled header is ignored, never a 500.
* **Propagation** — within a process the active span rides a
  ``contextvars.ContextVar``; across process-replica HTTP hops it
  rides the header (the hop span's id becomes the replica-side
  parent).  A replica that predates the header simply records nothing
  — the trace degrades to the router's single-process view.
* **Storage** — a bounded per-process ring (``MXNET_TRACE_RING``
  spans); overflow evicts oldest-first whole spans, counted, so a
  wrapped ring can never splice spans from two different traces into
  one record.
* **Export** — Chrome trace-event JSON via :func:`export` (served at
  ``GET /v1/trace`` on server and router), a ``trace`` profiler stats
  provider, and ``tools/traceview.py`` which merges router + replica
  dumps into one timeline by trace id.  Span timestamps are monotonic
  (mxlint MX-TIME001); export places them on a shared timeline via
  ONE wall-clock anchor captured per process.

Span vocabulary (what the instrumented call sites record):
``router.request`` / ``server.request`` roots; ``router.hop`` /
``router.hedge`` per physical attempt (each retry and hedge is its own
span, finishing with a typed ``outcome``); ``batch.queue`` /
``batch.execute`` (admission wait vs device compute, with the chosen
padding bucket); ``session.queue`` / ``session.decode_step``
(continuous batching); ``executor.build`` vs ``trace_cache.hit``
(compile-vs-cache on the Executor choke point); ``model.load``;
``train.epoch`` / ``train.chunk`` / ``prefetch.fill`` /
``prefetch.drain`` on the training side.  ``fault.py`` injections add
a ``fault.<point>`` event to the active span, so a chaos-run artifact
shows the injected fault and the recovery path in one timeline.  The
HA router tier adds ``router.forwarded`` events (mis-hashed session
request proxied to its ring owner, ``serving/routerha.py``) — the
``X-MXNET-ROUTER`` hop propagates the trace header, so a forwarded
request stays ONE trace across both routers.
"""
from __future__ import annotations

import contextvars
import json
import os
import random
import threading
import time
from collections import deque

from .base import get_env
from .locks import named_lock

__all__ = [
    "HEADER", "Span", "enabled", "active", "sample_rate", "configure",
    "reset", "start_trace", "start_child", "record_span", "from_header",
    "parse_header", "header_value", "current_span", "current_trace_id",
    "activate", "span", "add_event", "export", "spans", "stats",
    "health_block", "slow_k",
]

#: The propagation header: ``traceid(16 hex)-spanid(8 hex)-sampled``.
HEADER = "X-MXNET-TRACE"

_HEX = set("0123456789abcdef")

# ONE wall-clock anchor per process: every span timestamp is monotonic
# (durations can never jump on an NTP step — the MX-TIME001 contract);
# export maps them onto a shared cross-process timeline by adding the
# delta-to-anchor to this single wall reading.
_ANCHOR_WALL = time.time()  # mxlint: allow-wall-clock(single per-process anchor aligning monotonic span times across processes at export; all arithmetic stays monotonic)
_ANCHOR_MONO = time.monotonic()

_current: contextvars.ContextVar = contextvars.ContextVar(
    "mxnet_trace_span", default=None)

_lock = named_lock("trace.cfg")
_cfg = {"sample": None, "ring": None, "slow_k": None}  # None = env
_rng = random.Random()
_provider_registered = False


def _new_id(nibbles):
    return "%0*x" % (nibbles, _rng.getrandbits(4 * nibbles))


class Span:
    """One timed region of one trace.  Created by the helpers below;
    recorded into the ring at :meth:`finish` (never before — a crashed
    holder simply never lands, it cannot half-record)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "t1", "args", "events", "tid", "_done")

    def __init__(self, name, trace_id, parent_id=None, t0=None,
                 **args):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.t0 = time.monotonic() if t0 is None else float(t0)
        self.t1 = None
        self.args = dict(args)
        self.events = []           # [(t_mono, name, args), ...]
        self.tid = threading.get_ident()
        self._done = False

    def set(self, **args):
        self.args.update(args)
        return self

    def event(self, name, **args):
        """Timestamped instant event on this span (fault injections,
        cache hits, failover notes)."""
        self.events.append((time.monotonic(), name, args))

    def child(self, name, **args):
        return Span(name, self.trace_id, parent_id=self.span_id,
                    **args)

    def finish(self, outcome=None, t1=None):
        """Close the span and push it into the ring.  Idempotent —
        double-finish records once.  ``outcome`` defaults to ``"ok"``;
        error paths pass the typed error's class name."""
        if self._done:
            return self
        self._done = True
        self.t1 = time.monotonic() if t1 is None else float(t1)
        self.args.setdefault("outcome", outcome or "ok")
        _ring().push(self)
        return self

    @property
    def done(self):
        return self._done

    def duration_ms(self):
        end = self.t1 if self.t1 is not None else time.monotonic()
        return (end - self.t0) * 1000.0


# ---------------------------------------------------------------------------
# configuration + ring
# ---------------------------------------------------------------------------

def sample_rate():
    s = _cfg["sample"]
    if s is None:
        s = _cfg["sample"] = get_env("MXNET_TRACE_SAMPLE", 0.0, float)
    return s


def ring_capacity():
    n = _cfg["ring"]
    if n is None:
        n = _cfg["ring"] = max(
            1, get_env("MXNET_TRACE_RING", 4096, int))
    return n


def slow_k():
    """K for the slow-request exemplars the latency histograms keep
    (metrics.py); lives here so one module owns the trace knobs."""
    k = _cfg["slow_k"]
    if k is None:
        k = _cfg["slow_k"] = max(
            0, get_env("MXNET_TRACE_SLOW_K", 4, int))
    return k


def enabled():
    """Head sampling on (``MXNET_TRACE_SAMPLE`` > 0)."""
    return sample_rate() > 0.0


class _Ring:
    """Bounded span store.  Eviction is whole-span oldest-first, so a
    wrapped ring drops complete spans (counted) — it can never splice
    two traces into one record."""

    __slots__ = ("cap", "_d", "_lock", "pushed", "dropped")

    def __init__(self, cap):
        self.cap = int(cap)
        self._d = deque()
        self._lock = named_lock("trace.ring")
        self.pushed = 0
        self.dropped = 0

    def push(self, span_obj):
        with self._lock:
            self.pushed += 1
            self._d.append(span_obj)
            while len(self._d) > self.cap:
                self._d.popleft()
                self.dropped += 1
        _ensure_provider()

    def snapshot(self, trace_id=None):
        with self._lock:
            out = list(self._d)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def clear(self):
        with self._lock:
            self._d.clear()
            self.pushed = 0
            self.dropped = 0


_ring_obj = None


def _ring():
    global _ring_obj
    if _ring_obj is None:
        with _lock:
            if _ring_obj is None:
                _ring_obj = _Ring(ring_capacity())
    return _ring_obj


def configure(sample=None, ring=None, slow=None):
    """Programmatic override of the env knobs (tests, benches).  Any
    argument left ``None`` keeps its current value; changing the ring
    capacity re-allocates an empty ring."""
    global _ring_obj
    with _lock:
        if sample is not None:
            _cfg["sample"] = float(sample)
        if slow is not None:
            _cfg["slow_k"] = int(slow)
        if ring is not None:
            _cfg["ring"] = max(1, int(ring))
            _ring_obj = _Ring(_cfg["ring"])
    if sample is not None and sample > 0:
        _ensure_provider()


def reset():
    """Forget overrides and recorded spans; next use re-reads the env
    (test isolation)."""
    global _ring_obj
    with _lock:
        _cfg["sample"] = None
        _cfg["ring"] = None
        _cfg["slow_k"] = None
        _ring_obj = None


def active():
    """Tracing is observably on: sampling enabled, or spans already
    recorded (an adopted forced-sample header counts).  Gates the
    additive ``"trace"`` block in /healthz + describe()."""
    return enabled() or (_ring_obj is not None and _ring_obj.pushed > 0)


def _ensure_provider():
    global _provider_registered
    if _provider_registered:
        return
    _provider_registered = True
    from . import profiler
    profiler.register_stats_provider("trace", stats)


# ---------------------------------------------------------------------------
# creation + context propagation
# ---------------------------------------------------------------------------

def start_trace(name, **args):
    """Head-sampled root span: returns a :class:`Span` or ``None``
    (the per-request sampling branch — when ``MXNET_TRACE_SAMPLE`` is
    0 this is one float compare)."""
    rate = sample_rate()
    if rate <= 0.0:
        return None
    if rate < 1.0 and _rng.random() >= rate:
        return None
    return Span(name, _new_id(16), **args)


def start_child(name, parent=None, **args):
    """Child span of ``parent`` (default: the context's current span);
    ``None`` parent ⇒ ``None`` (unsampled requests stay free)."""
    p = parent if parent is not None else _current.get()
    if p is None:
        return None
    return p.child(name, **args)


def record_span(name, parent, t0, t1, **args):
    """Create AND finish a child span with explicit monotonic
    timestamps — for recorders that learn about a region after the
    fact (the batcher's queue-wait split)."""
    if parent is None:
        return None
    s = parent.child(name, t0=t0, **args)
    return s.finish(t1=t1)


def current_span():
    return _current.get()


def current_trace_id():
    s = _current.get()
    return s.trace_id if s is not None else None


class activate:
    """``with trace.activate(span):`` — install ``span`` as the
    context's current span (``None`` ⇒ no-op passthrough, so callers
    need no branch)."""

    __slots__ = ("_span", "_token")

    def __init__(self, span_obj):
        self._span = span_obj
        self._token = None

    def __enter__(self):
        if self._span is not None:
            self._token = _current.set(self._span)
        return self._span

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
        return False


class span:
    """``with trace.span("router.hop", replica=rid):`` — child of the
    current span, activated for the body, finished on exit with
    ``outcome`` = the escaping exception's class name (or "ok").
    No current span ⇒ complete no-op."""

    __slots__ = ("_name", "_args", "_span", "_token")

    def __init__(self, name, **args):
        self._name = name
        self._args = args
        self._span = None
        self._token = None

    def __enter__(self):
        parent = _current.get()
        if parent is not None:
            self._span = parent.child(self._name, **self._args)
            self._token = _current.set(self._span)
        return self._span

    def __exit__(self, etype, evalue, tb):
        if self._token is not None:
            _current.reset(self._token)
        if self._span is not None:
            self._span.finish(
                outcome=etype.__name__ if etype is not None else None)
        return False


def add_event(name, **args):
    """Instant event on the active span, if any — the hook fault.py
    fires on every injection (one contextvar read when untraced)."""
    s = _current.get()
    if s is not None:
        s.event(name, **args)


# ---------------------------------------------------------------------------
# header propagation
# ---------------------------------------------------------------------------

def parse_header(text):
    """``traceid-spanid-sampled`` → ``(trace_id, span_id, sampled)``;
    ``None`` for anything malformed (a garbled header is ignored, not
    an error — the request must still serve)."""
    if not text or not isinstance(text, str):
        return None
    parts = text.strip().lower().split("-")
    if len(parts) != 3:
        return None
    tid, sid, flag = parts
    if len(tid) != 16 or not set(tid) <= _HEX:
        return None
    if len(sid) != 8 or not set(sid) <= _HEX:
        return None
    if flag not in ("0", "1"):
        return None
    return tid, sid, flag == "1"


def header_value(span_obj):
    """The ``X-MXNET-TRACE`` value carrying ``span_obj`` downstream
    (its id becomes the callee-side parent); ``None`` span ⇒ ``None``
    (caller sends no header)."""
    if span_obj is None:
        return None
    return f"{span_obj.trace_id}-{span_obj.span_id}-1"


def from_header(text, name, **args):
    """Adopt a propagated trace, or fall back to the local sampling
    decision.  A valid header is AUTHORITATIVE either way: sampled=1
    records regardless of local sampling (the head decision was
    upstream's), sampled=0 suppresses recording entirely (the
    upstream already decided not to trace this request); only a
    garbled/absent header degrades to :func:`start_trace`."""
    parsed = parse_header(text)
    if parsed is None:
        return start_trace(name, **args)
    tid, parent_sid, sampled = parsed
    if not sampled:
        return None
    s = Span(name, tid, parent_id=parent_sid, **args)
    s.args["adopted"] = True
    return s


# ---------------------------------------------------------------------------
# export + stats
# ---------------------------------------------------------------------------

def anchor():
    """The per-process ``(wall, monotonic)`` anchor pair.  Captured
    ONCE per process (the MX-TIME001 contract) and shared by every
    exporter that needs to place monotonic timestamps on a cross-
    process timeline — this module's span export and the flight
    recorder's event dumps both use it, so their merged timelines can
    never disagree about when "now" was."""
    return _ANCHOR_WALL, _ANCHOR_MONO


def _wall_us(t_mono):
    return int((_ANCHOR_WALL + (t_mono - _ANCHOR_MONO)) * 1e6)


def spans(trace_id=None):
    """Recorded spans, newest last (optionally one trace's)."""
    return _ring().snapshot(trace_id)


def export(trace_id=None, service=None):
    """Chrome trace-event JSON (``chrome://tracing`` /
    ``ui.perfetto.dev`` loadable): one ``ph:"X"`` complete event per
    span, one ``ph:"i"`` instant per span event.  ``service`` labels
    the process (router/replica) for merged views."""
    pid = os.getpid()
    svc = service or f"pid:{pid}"
    events = []
    for s in _ring().snapshot(trace_id):
        t1 = s.t1 if s.t1 is not None else s.t0
        args = dict(s.args)
        args.update(trace_id=s.trace_id, span_id=s.span_id,
                    parent_id=s.parent_id, service=svc)
        events.append({
            "name": s.name, "cat": "trace", "ph": "X",
            "ts": _wall_us(s.t0),
            "dur": max(0, _wall_us(t1) - _wall_us(s.t0)),
            "pid": pid, "tid": s.tid, "args": args,
        })
        for t_ev, ev_name, ev_args in s.events:
            ia = dict(ev_args)
            ia.update(trace_id=s.trace_id, span_id=s.span_id,
                      service=svc)
            events.append({
                "name": ev_name, "cat": "trace_event", "ph": "i",
                "ts": _wall_us(t_ev), "s": "t",
                "pid": pid, "tid": s.tid, "args": ia,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_json(trace_id=None, service=None):
    return json.dumps(export(trace_id, service))


def stats():
    """The ``trace`` profiler stats provider."""
    r = _ring()
    with r._lock:
        in_ring = len(r._d)
        pushed, dropped = r.pushed, r.dropped
        traces = len({s.trace_id for s in r._d})
    return {
        "enabled": enabled(),
        "sample": sample_rate(),
        "ring_capacity": r.cap,
        "spans_recorded": pushed,
        "spans_dropped": dropped,
        "spans_in_ring": in_ring,
        "traces_in_ring": traces,
        "slow_k": slow_k(),
    }


def health_block():
    """The additive ``"trace"`` block for /healthz + describe() —
    present only while :func:`active` (bare deployments keep their
    pinned shape)."""
    st = stats()
    return {"sample": st["sample"], "ring": st["ring_capacity"],
            "spans": st["spans_recorded"],
            "dropped": st["spans_dropped"], "slow_k": st["slow_k"]}
