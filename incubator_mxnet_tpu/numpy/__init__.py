"""``mx.np``: NumPy-compatible array namespace.

Reference: python/mxnet/numpy/ (14.5 kLoC of `_npi.*` wrappers over
src/operator/numpy/).  TPU design: ``mx.np.ndarray`` IS the framework
NDArray (one data plane) and the function namespace delegates straight
to jnp — jax.numpy already implements NumPy semantics on XLA, so the
reference's 26.8 kLoC of NumPy-semantics kernels collapse into this
dispatch layer.  Autograd still applies: functions route through the op
registry when an op exists, else wrap jnp directly (recorded via the
generic ``_jnp_call`` vjp path).
"""
from __future__ import annotations

import builtins as _bi
import functools

import numpy as _onp
import jax
import jax.numpy as _jnp

from ..base import dtype_from_any as _dtype_from_any
from ..context import current_context
from ..ndarray import NDArray as ndarray  # mx.np.ndarray IS NDArray
from ..ndarray import NDArray as _ND
from .. import autograd as _autograd

pi = _jnp.pi
e = _jnp.e
inf = _jnp.inf
nan = _jnp.nan
newaxis = None

float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int32 = _onp.int32
int64 = _onp.int64
int8 = _onp.int8
uint8 = _onp.uint8
bool_ = _onp.bool_


def _wrap_fn(jnp_fn):
    """Lift a jnp function to NDArray in/out with autograd recording."""

    @functools.wraps(jnp_fn)
    def fn(*args, **kwargs):
        # the vjp below covers ALL positional args; record the true
        # argument slot of each NDArray so backward() maps cotangents
        # correctly when scalars precede arrays (np.subtract(1.0, x)).
        # Sequence args (np.concatenate([a, b])) unwrap one level deep
        # with compound (slot, index) addresses.
        nd_inputs, nd_slots, raw = [], [], []
        for i, a in enumerate(args):
            if isinstance(a, _ND):
                nd_inputs.append(a)
                nd_slots.append(i)
                raw.append(a.data)
            elif isinstance(a, (list, tuple)) and _bi.any(
                    isinstance(e, _ND) for e in a):
                for j, e in enumerate(a):
                    if isinstance(e, _ND):
                        nd_inputs.append(e)
                        nd_slots.append((i, j))
                raw.append(type(a)(
                    e.data if isinstance(e, _ND) else e for e in a))
            else:
                raw.append(a)

        # NB: _bi.any — the delegated namespace below shadows several
        # builtins (np.any/all/sum/...) in this module's globals, and a
        # bare any() here recursed through its own wrapper
        recording = _autograd.is_recording() and _bi.any(
            a._in_graph() for a in nd_inputs)
        def call(*xs):
            res = jnp_fn(*xs, **kwargs)
            # normalize list outputs (jnp.split et al.) to tuples so the
            # vjp's primal structure matches the tuple cotangent seed
            # backward() builds (jax.vjp requires exact pytree match)
            return tuple(res) if isinstance(res, list) else res
        if recording:
            try:
                out, vjp = jax.vjp(call, *raw)
            except TypeError:
                out, vjp = call(*raw), None
        else:
            out, vjp = call(*raw), None
        if isinstance(out, (tuple, list)):
            outs = tuple(_ND(o) for o in out)
        else:
            outs = _ND(out)
        if vjp is not None:
            out_tuple = outs if isinstance(outs, tuple) else (outs,)

            def tape_vjp(seed):
                if isinstance(outs, tuple) and not isinstance(seed, tuple):
                    seed = (seed,)
                return vjp(seed)

            _autograd._record(None, tape_vjp, args, nd_inputs,
                              nd_slots, out_tuple, fn=call)
        return outs

    return fn


# Expose the bulk of the numpy namespace by delegation
_DELEGATED = [
    "abs", "absolute", "add", "all", "amax", "amin", "any", "arange_like",
    "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctan2", "arctanh",
    "argmax", "argmin", "argsort", "around", "atleast_1d", "atleast_2d",
    "broadcast_arrays", "cbrt", "ceil", "clip", "column_stack",
    "concatenate", "copysign", "cos", "cosh", "cross", "cumprod", "cumsum",
    "deg2rad", "degrees", "diag", "diag_indices", "diagonal", "diff",
    "divide", "dot", "dsplit", "dstack", "ediff1d", "einsum", "equal", "exp",
    "expand_dims", "expm1", "fix", "flip", "fliplr", "flipud", "floor",
    "floor_divide", "fmax", "fmin", "fmod", "greater", "greater_equal",
    "heaviside", "histogram", "hsplit", "hstack", "hypot", "insert",
    "interp", "invert", "isfinite", "isinf", "isnan", "kron", "lcm",
    "gcd", "less", "less_equal", "log", "log10", "log1p", "log2",
    "logaddexp", "logical_and", "logical_not", "logical_or", "logical_xor",
    "matmul", "maximum", "mean", "median", "min", "max", "minimum", "mod",
    "moveaxis", "multiply", "nan_to_num", "nanargmax", "nanargmin",
    "nancumsum", "nanmax", "nanmean", "nanmin", "nanprod", "nanstd",
    "nansum", "nanvar", "negative", "not_equal", "outer", "percentile",
    "polyval", "positive", "power", "prod", "ptp", "quantile", "rad2deg",
    "radians", "ravel", "reciprocal", "remainder", "repeat", "reshape",
    "rint", "broadcast_to", "roll", "rot90", "round", "searchsorted", "sign", "sin", "sinh",
    "sort", "split", "sqrt", "square", "squeeze", "stack", "std",
    "subtract", "sum", "swapaxes", "take", "take_along_axis", "tan", "tanh",
    "tensordot", "tile", "trace", "transpose", "tril", "triu",
    "true_divide", "trunc", "unique", "unravel_index", "vdot", "vsplit",
    "vstack", "var", "where", "count_nonzero", "nonzero", "delete",
    "pad", "flatnonzero", "meshgrid", "average", "bincount", "corrcoef",
    "correlate", "cov", "digitize", "divmod", "float_power", "frexp",
    "inner", "isclose", "isneginf", "isposinf", "ldexp", "nanmedian",
    "nanpercentile", "nanquantile", "signbit", "sinc", "spacing",
]

_g = globals()
for _name in _DELEGATED:
    if hasattr(_jnp, _name) and _name not in _g:
        _g[_name] = _wrap_fn(getattr(_jnp, _name))


class _Linalg:
    def __getattr__(self, name):
        return _wrap_fn(getattr(_jnp.linalg, name))


class _FFT:
    def __getattr__(self, name):
        return _wrap_fn(getattr(_jnp.fft, name))


linalg = _Linalg()
fft = _FFT()


class _NPRandom:
    """mx.np.random — eager samplers over the global key stream."""

    def __getattr__(self, name):
        from .. import random as _gr

        jr_fn = getattr(jax.random, name, None)

        def fn(*args, size=None, **kwargs):
            key = _gr.next_key()
            if name == "uniform":
                low, high = (args + (0.0, 1.0))[:2]
                return _ND(jax.random.uniform(
                    key, _as_shape(size), minval=low, maxval=high))
            if name in ("normal", "randn"):
                loc, scale = (args + (0.0, 1.0))[:2] if name == "normal" \
                    else (0.0, 1.0)
                shape = _as_shape(size) if name == "normal" else tuple(args)
                return _ND(loc + scale * jax.random.normal(key, shape))
            if name == "randint":
                low = args[0]
                high = args[1] if len(args) > 1 else None
                if high is None:
                    low, high = 0, low
                return _ND(jax.random.randint(key, _as_shape(size), low, high))
            if name == "choice":
                return _ND(jax.random.choice(
                    key, args[0].data if isinstance(args[0], _ND) else args[0],
                    shape=_as_shape(size), **kwargs))
            if jr_fn is None:
                raise AttributeError(f"np.random.{name}")
            return _ND(jr_fn(key, *args, **kwargs))

        return fn

    @staticmethod
    def seed(s):
        from .. import random as _gr
        _gr.seed(s)


def _as_shape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


random = _NPRandom()


# creation ops need ctx placement
def array(obj, dtype=None, ctx=None):
    return _ND(obj, ctx=ctx or current_context(), dtype=dtype)


def asarray(obj, dtype=None):
    if isinstance(obj, _ND):
        return obj.astype(dtype) if dtype else obj
    return array(obj, dtype=dtype)


def zeros(shape, dtype="float32", ctx=None, order="C"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ND(_jnp.zeros(shape, _dtype_from_any(dtype)),
               ctx=ctx or current_context())


def ones(shape, dtype="float32", ctx=None, order="C"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ND(_jnp.ones(shape, _dtype_from_any(dtype)),
               ctx=ctx or current_context())


def full(shape, fill_value, dtype=None, ctx=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _ND(_jnp.full(shape, fill_value,
                         _dtype_from_any(dtype) if dtype else None),
               ctx=ctx or current_context())


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype, ctx)


def zeros_like(a, dtype=None):
    return _ND(_jnp.zeros_like(a.data if isinstance(a, _ND) else a,
                               dtype=_dtype_from_any(dtype) if dtype else None))


def ones_like(a, dtype=None):
    return _ND(_jnp.ones_like(a.data if isinstance(a, _ND) else a,
                              dtype=_dtype_from_any(dtype) if dtype else None))


def full_like(a, fill_value, dtype=None):
    return _ND(_jnp.full_like(a.data if isinstance(a, _ND) else a, fill_value))


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return _ND(_jnp.arange(start, stop, step,
                           _dtype_from_any(dtype) if dtype else None),
               ctx=ctx or current_context())


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    out = _jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                        dtype=_dtype_from_any(dtype) if dtype else None,
                        axis=axis)
    if retstep:
        return _ND(out[0]), float(out[1])
    return _ND(out, ctx=ctx or current_context())


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    return _ND(_jnp.logspace(start, stop, num, endpoint, base))


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return _ND(_jnp.eye(N, M, k, _dtype_from_any(dtype)))


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def may_share_memory(a, b):
    if isinstance(a, _ND) and isinstance(b, _ND):
        return a._chunk is b._chunk
    return False


def shares_memory(a, b):
    return may_share_memory(a, b)
