"""Autoscaling control plane: scale-from-zero, HBM bin-packing, SLO
classes over the replica fleet.

The fleet (PR 8) and router (PR 8/11) serve a *fixed* N replicas of one
model set; production traffic is hundreds of models with diurnal load.
This module closes the loop the ROADMAP (item 3) calls for: a control
loop driven by the router's own metrics that grows and shrinks the
fleet **per model**, made affordable by two earlier PRs —

* **Scale-from-zero is cheap** because of the AOT artifact path
  (PR 10): loading a model whose artifact carries per-bucket compiled
  executables is deserialization, not compilation, so an idle model
  can be unloaded after ``MXNET_SERVING_IDLE_UNLOAD_S`` and the first
  request after scale-to-zero pays well under a second
  (``mxnet_serving_compile_total`` does not move).
* **Bin-packing has an honest budget** because of memlint (PR 9):
  every artifact records its forward's peak-HBM estimate, so multiple
  models pack onto one replica under
  ``MXNET_SERVING_REPLICA_HBM_BUDGET`` with least-recently-used
  eviction when a load would exceed it (:mod:`.placement`).

The loop (one :meth:`Autoscaler.run_once` per
``MXNET_SERVING_SCALE_INTERVAL_S``):

1. **Sense** — per-model queue depth from each replica's vitals,
   inflight/p99/idle from the router's :class:`~.metrics.FleetMetrics`.
2. **Decide** — a desired replica count per model: one step up when
   the per-replica backlog crosses ``MXNET_SERVING_SCALE_QUEUE_HIGH``,
   one step down when it collapses, down to ``min_replicas`` (0 ⇒
   scale-to-zero) once idle past the unload threshold.
3. **Place** — grows go through the :class:`~.placement.Placer`
   (best-fit under the HBM budget, LRU eviction, spawn a new replica
   when nothing fits and the fleet is under
   ``MXNET_SERVING_SCALE_MAX_REPLICAS``).
4. **Apply** — every action fires the ``serving.scale`` fault point
   first; an injected fault drops that decision for the tick and the
   next tick re-derives it from live state (the loop is level-
   triggered, so chaos can only delay convergence, never corrupt it).

**Sessions are first-class**: a replica picked for shrink begins
draining immediately but is only closed once its in-flight requests
and active decode streams have reached a step boundary (sessions keep
stepping on DRAINING replicas); the close then snapshots every session
synchronously, so the router's migrate-from-snapshot failover resumes
them losslessly on a survivor — a shrink never breaks a stream
mid-carry.

Everything is metrics-visible (desired-vs-actual gauges, decision and
eviction counters, integrated replica-seconds) and chaos-testable
(``serving.scale`` in the ``autoscale`` CI stage's pinned spec).
"""
from __future__ import annotations

import logging
import threading
import time

from ..base import get_env
from .. import fault, flightrec
from ..error import (FleetDrainingError, ModelEvictedError,
                     ReplicaUnavailableError)
from ..locks import named_lock
from .admission import ModelNotFound, slo_class
from .placement import Placer, model_footprint_bytes

__all__ = ["Autoscaler", "ModelPolicy"]

_log = logging.getLogger("incubator_mxnet_tpu.serving.autoscaler")


class ModelPolicy:
    """Per-model scaling policy: where the model's artifact lives, how
    many copies it may have, and which SLO tier it serves under.

    ``min_replicas=0`` opts the model into scale-to-zero: after
    ``MXNET_SERVING_IDLE_UNLOAD_S`` without a request it is unloaded
    everywhere, and the next request reloads it on demand through the
    AOT path (the router blocks that one request on the load instead
    of 404ing).  ``footprint_bytes`` overrides the artifact's memlint
    peak-HBM estimate for the bin-packer."""

    def __init__(self, name, path, slo="standard", min_replicas=0,
                 max_replicas=None, target_queue=None,
                 footprint_bytes=None, warmup=None):
        self.name = name
        self.path = path
        self.slo = slo_class(slo)
        self.min_replicas = int(min_replicas)
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        if self.min_replicas < 0:
            raise ValueError(
                f"min_replicas must be >= 0, got {self.min_replicas}")
        if (self.max_replicas is not None
                and self.max_replicas < max(1, self.min_replicas)):
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas for "
                f"model {name!r}")
        self.target_queue = (None if target_queue is None
                             else float(target_queue))
        self.footprint_bytes = (None if footprint_bytes is None
                                else int(footprint_bytes))
        self.warmup = warmup

    def footprint(self):
        if self.footprint_bytes is None:
            self.footprint_bytes = model_footprint_bytes(self.path)
        return self.footprint_bytes

    def __repr__(self):
        return (f"ModelPolicy({self.name!r}, slo={self.slo.name}, "
                f"min={self.min_replicas}, max={self.max_replicas})")


class Autoscaler:
    """The control loop over one :class:`~.fleet.ReplicaFleet`.

    ``router`` (a :class:`~.router.FleetRouter`) is optional but is
    where the interesting signals live — attaching wires the router's
    on-demand scale-from-zero path (``router.autoscaler``) and the
    desired-vs-actual metrics into its ``/metrics`` and ``/healthz``.
    Construct, :meth:`add_policy` the models, then :meth:`start` (or
    drive :meth:`run_once` directly from tests/benches)."""

    def __init__(self, fleet, router=None, policies=(), placer=None,
                 interval_s=None, idle_unload_s=None,
                 queue_high=None, max_replicas=None, min_fleet=1,
                 drain_s=None, metrics=None):
        self.fleet = fleet
        self.router = router
        self.metrics = (metrics if metrics is not None
                        else getattr(router, "metrics", None))
        self.placer = placer or Placer()
        self.interval_s = float(
            interval_s if interval_s is not None
            else get_env("MXNET_SERVING_SCALE_INTERVAL_S", 2.0, float))
        self.idle_unload_s = float(
            idle_unload_s if idle_unload_s is not None
            else get_env("MXNET_SERVING_IDLE_UNLOAD_S", 300.0, float))
        self.queue_high = float(
            queue_high if queue_high is not None
            else get_env("MXNET_SERVING_SCALE_QUEUE_HIGH", 4.0, float))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else get_env("MXNET_SERVING_SCALE_MAX_REPLICAS", 4, int))
        self.min_fleet = int(min_fleet)
        self.drain_s = float(
            drain_s if drain_s is not None
            else get_env("MXNET_SERVING_SCALE_DRAIN_S", 30.0, float))
        if self.interval_s <= 0 or self.queue_high <= 0:
            raise ValueError(
                "MXNET_SERVING_SCALE_INTERVAL_S and "
                "MXNET_SERVING_SCALE_QUEUE_HIGH must be > 0")
        if self.max_replicas < 1 or self.min_fleet < 1:
            raise ValueError(
                "MXNET_SERVING_SCALE_MAX_REPLICAS and min_fleet must "
                "be >= 1")
        self._policies: dict[str, ModelPolicy] = {}
        for p in policies:
            self.add_policy(p)
        self._lock = named_lock("autoscaler.state")
        self._demand_locks: dict = {}
        # planning is serialized and RESERVES budget in the ledger at
        # plan time (see _plan_grow): two grow decisions derived
        # against the same books — two models crossing the threshold
        # in one tick, or the background loop racing an on-demand
        # ensure_loaded — must not jointly overcommit one replica's
        # HBM budget.  _reserved marks in-flight loads so _sync_placer
        # does not drop the reservation before the load lands.
        self._plan_lock = named_lock("autoscaler.plan")
        self._reserved: set = set()            # {(rid, model)}
        # in-flight spawns count against the replica ceiling from PLAN
        # time: a spawn decision racing a second planner (two
        # on-demand ensure_loaded calls, or the background loop) used
        # to let both see the pre-spawn fleet size and jointly
        # overshoot MXNET_SERVING_SCALE_MAX_REPLICAS by one
        self._spawns_pending = 0
        self._counters = {"scale_up": 0, "scale_down": 0, "spawn": 0,
                          "shrink": 0, "evict": 0, "faults": 0,
                          "blocked": 0, "scale_from_zero": 0}
        self._evictions: dict[str, int] = {}
        self._scale_from_zero_ms: dict[str, float] = {}
        self._last_desired: dict[str, int] = {}
        self._shrinking: dict[str, float] = {}    # rid -> deadline
        self._replica_seconds = 0.0
        self._t_last_tick = time.monotonic()
        self._stop = threading.Event()
        self._thread = None
        self._sync_placer()
        if self.metrics is not None:
            self.metrics.attach_autoscaler(self.describe)
        if router is not None:
            router.autoscaler = self

    # -- policy surface ------------------------------------------------

    def add_policy(self, policy):
        self._policies[policy.name] = policy
        return policy

    def manages(self, name):
        return name in self._policies

    def policy(self, name):
        return self._policies[name]

    def policies(self):
        return dict(self._policies)

    # -- bookkeeping ---------------------------------------------------

    def _live_replicas(self):
        from .fleet import DEAD, DRAINING
        return [r for r in self.fleet.replicas
                if r.state not in (DEAD, DRAINING)]

    def _sync_placer(self):
        """Reconcile the placement ledger with the live fleet: adopt
        pre-loaded model sets (a classic ``spawn()``), forget dead or
        removed replicas — killed replicas free their budget."""
        live = {r.rid: r for r in self._live_replicas()}
        for rid, r in live.items():
            self.placer.register_replica(rid)
            on = self.placer.models_on(rid)
            for name, path in r.models.items():
                if name not in on:
                    p = self._policies.get(name)
                    nbytes = (p.footprint() if p is not None
                              else model_footprint_bytes(path))
                    self.placer.record_load(rid, name, nbytes)
            for name in list(on):
                with self._lock:
                    reserved = (rid, name) in self._reserved
                if name not in r.models and not reserved:
                    self.placer.record_unload(rid, name)
        for rid in list(self.placer.assignments()):
            if rid not in live and rid not in self._shrinking:
                self.placer.forget_replica(rid)

    def actual(self, name):
        """Replica copies of ``name`` currently live (the gauge next
        to ``desired``)."""
        live = {r.rid for r in self._live_replicas()}
        return len([rid for rid in self.placer.replicas_of(name)
                    if rid in live])

    def replica_seconds(self):
        """Integrated live-replica time since construction — the
        fleet-economics number the autoscale bench compares against a
        static fleet (``peak_replicas * wall_time``)."""
        with self._lock:
            now = time.monotonic()
            self._replica_seconds += (
                len(self._live_replicas()) * (now - self._t_last_tick))
            self._t_last_tick = now
            return self._replica_seconds

    def _model_idle_s(self, name):
        if self.metrics is None:
            return float("inf")
        return self.metrics.model_idle_s(name)

    # -- sense + decide ------------------------------------------------

    def _collect_vitals(self):
        """ONE combined probe per live replica (``replica.vitals()``
        — a single /healthz round trip on the process backend):
        ``{rid: {"queues":…, "sessions":…, "streams":…}}``.  Shared
        by every consumer of a tick so the control loop's I/O stays
        one probe per replica, not one per signal."""
        out = {}
        for r in self._live_replicas():
            try:
                out[r.rid] = r.vitals()
            except Exception:  # mxlint: allow-broad-except(a replica dying mid-probe simply contributes no load signal this tick)
                out[r.rid] = {"queues": {}, "sessions": 0,
                              "streams": 0}
        return out

    def signals(self, vitals=None):
        """One sensing sweep: ``{model: {queued, inflight, p99_ms,
        idle_s, actual}}`` for every managed model (plus any model a
        replica reports vitals for)."""
        vitals = (vitals if vitals is not None
                  else self._collect_vitals())
        queued: dict[str, int] = {}
        for v in vitals.values():
            for name, depth in v["queues"].items():
                queued[name] = queued.get(name, 0) + int(depth)
        stats = (self.metrics.model_stats()
                 if self.metrics is not None else {})
        out = {}
        for name in set(self._policies) | set(queued):
            st = stats.get(name, {})
            out[name] = {
                "queued": queued.get(name, 0),
                "inflight": st.get("inflight", 0),
                "p99_ms": st.get("p99_ms", 0.0),
                "idle_s": st.get("idle_s", self._model_idle_s(name)),
                "actual": self.actual(name),
            }
        return out

    def desired(self, signals=None):
        """The level-triggered decision: desired copies per managed
        model.  One step per tick in either direction — the loop
        converges over ticks rather than thrashing on a noisy
        signal."""
        signals = signals if signals is not None else self.signals()
        out = {}
        for name, p in self._policies.items():
            sig = signals.get(name, {})
            a = sig.get("actual", 0)
            load = sig.get("queued", 0) + sig.get("inflight", 0)
            idle = sig.get("idle_s", float("inf"))
            cap = min(self.max_replicas,
                      p.max_replicas if p.max_replicas is not None
                      else self.max_replicas)
            floor = p.min_replicas
            high = (p.target_queue if p.target_queue is not None
                    else self.queue_high)
            if a == 0:
                # scaled to zero: stay there until a request arrives
                # (the router's on-demand path handles the first one)
                want, why = floor, "at_zero"
            elif load / a >= high:
                want, why = a + 1, "backlog_high"
            elif load == 0 and idle >= self.idle_unload_s:
                want, why = floor, "idle"   # unload toward zero
            elif a > 1 and load / (a - 1) < high * 0.5:
                want, why = a - 1, "slack"  # smaller fleet suffices
            else:
                want, why = a, None
            out[name] = max(floor, min(cap, want))
            if out[name] != a and why is not None:
                # the DECISION and the signal that tripped it — the
                # record a postmortem explains a bad scale-down from
                flightrec.record(
                    flightrec.SCALING, "scale.decide", model=name,
                    actual=a, desired=out[name], why=why,
                    queued=sig.get("queued", 0),
                    inflight=sig.get("inflight", 0),
                    idle_s=None if idle == float("inf")
                    else round(idle, 1))
        self._last_desired = dict(out)
        return out

    def evaluate(self):
        """Derive this tick's scale decisions.  Grow plans RESERVE
        their budget in the ledger as they are made (under
        ``_plan_lock``), so two models crossing the threshold in one
        tick cannot both be planned into the same free bytes; a plan
        that is later dropped rolls its reservation back
        (:meth:`_apply_one`)."""
        vitals = self._collect_vitals()
        with self._plan_lock:
            self._sync_placer()
            signals = self.signals(vitals)
            desired = self.desired(signals)
            decisions = []
            try:
                # highest-priority models place first: when budget is
                # tight the interactive tier wins the bin-packing race
                for name in sorted(
                        desired,
                        key=lambda n: (self._policies[n].slo.priority,
                                       n)):
                    p = self._policies[name]
                    a = signals.get(name, {}).get("actual",
                                                  self.actual(name))
                    d = desired[name]
                    if d > a:
                        decisions.append(
                            self._plan_grow(name, p, desired))
                    elif d < a:
                        rid = self._pick_unload(name, vitals)
                        if rid is not None:
                            decisions.append({"action": "unload",
                                              "model": name,
                                              "rid": rid})
                decisions.extend(self._plan_shrinks(vitals))
            except BaseException:
                # a crash mid-planning (run_once logs and drops the
                # tick) must not strand the ledger bytes / ceiling
                # slots the completed plans already reserved
                for d in decisions:
                    if d is not None:
                        self._rollback(d)
                raise
            # wait_spawn is demand-path-only: the background loop
            # re-derives from live state next tick anyway
            decisions = [d for d in decisions
                         if d is not None and d["action"] != "wait_spawn"]
        return decisions

    def _plan_grow(self, name, policy, desired):
        """One more copy of ``name``: best-fit placement, then a fresh
        replica while the fleet has headroom, and only then LRU
        eviction of lower-priority/idle tenants — evicting a live
        model is the last resort, never a convenience."""
        live = self._live_replicas()
        candidates = [r.rid for r in live]
        rid, _ = self.placer.choose(
            name, policy.footprint(), candidates, evict=False)
        if rid is not None:
            self._reserve(rid, name, policy.footprint())
            return {"action": "load", "model": name, "rid": rid,
                    "evict": []}
        with self._lock:
            pending = self._spawns_pending
        if len(live) + pending < self.max_replicas:
            # the slot is claimed under _plan_lock; _release_spawn
            # returns it once the spawn lands (the replica then counts
            # as live) or the decision is dropped
            with self._lock:
                self._spawns_pending += 1
            return {"action": "spawn_load", "model": name,
                    "_spawn_reserved": True}
        if pending and len(live) < self.max_replicas:
            # the ceiling is consumed by a spawn still in flight — not
            # a capacity verdict: the demand path retries and places
            # onto the replica once it lands; the background loop just
            # re-derives next tick
            return {"action": "wait_spawn", "model": name}
        # strictly higher tiers are untouchable; within a tier the
        # budget is a working set and LRU decides who pages out — an
        # oversubscribed fleet must thrash at the margin, not deadlock
        protected = {
            m for m, pol in self._policies.items()
            if desired.get(m, 0) > 0
            and pol.slo.priority < policy.slo.priority}
        protected.add(name)
        # unmanaged models were placed by an operator, not this loop —
        # never evict what we do not own
        for r in live:
            for m in r.models:
                if m not in self._policies:
                    protected.add(m)
        # an in-flight reservation is not yet a loadable/unloadable
        # model on the replica: evicting it would unload nothing and
        # double-book the bytes it claimed
        with self._lock:
            protected |= {m for _rid, m in self._reserved}
        rid, evictions = self.placer.choose(
            name, policy.footprint(), candidates,
            idle_s_fn=self._model_idle_s, protected=protected)
        if rid is not None:
            self._reserve(rid, name, policy.footprint())
            return {"action": "load", "model": name, "rid": rid,
                    "evict": evictions}
        with self._lock:
            self._counters["blocked"] += 1
        flightrec.record(flightrec.PLACEMENT, "placer.blocked",
                         severity="warn", model=name,
                         tier=policy.slo.name,
                         footprint=policy.footprint())
        return None

    def _reserve(self, rid, name, nbytes):
        """Claim budget for an in-flight load at PLAN time: the ledger
        entry stops concurrent planners handing the same free bytes to
        another model; the marker stops ``_sync_placer`` dropping the
        claim before the (possibly slow) load lands."""
        with self._lock:
            self._reserved.add((rid, name))
        self.placer.record_load(rid, name, nbytes)

    def _unreserve(self, rid, name, loaded):
        """Resolve a reservation: a landed load keeps its ledger entry
        (now backed by ``replica.models``); a dropped/failed plan rolls
        the claimed bytes back."""
        with self._lock:
            self._reserved.discard((rid, name))
        if not loaded:
            self.placer.record_unload(rid, name)

    def _pick_unload(self, name, vitals):
        """Which copy to retire: the replica where the model is doing
        the least (fewest queued for it, then least loaded overall).
        ``vitals`` is the tick's shared probe sweep."""
        live = {r.rid: r for r in self._live_replicas()}
        holders = [live[rid] for rid in self.placer.replicas_of(name)
                   if rid in live]
        if not holders:
            return None

        def load_of(r):
            v = vitals.get(r.rid)
            if v is None:
                return (-1, -1)   # unreachable: cheapest to retire
            return (v["queues"].get(name, 0), r.inflight)

        return min(holders, key=lambda r: (load_of(r), r.rid)).rid

    def _plan_shrinks(self, vitals):
        """Empty replicas (no models, no sessions) above the fleet
        floor begin draining; quiesced draining replicas close."""
        out = []
        live = self._live_replicas()
        floor = self.min_fleet
        empty = [r for r in live
                 if not r.models
                 and not self.placer.models_on(r.rid)
                 and vitals.get(r.rid, {}).get("sessions", 0) == 0]
        can_drop = len(live) - floor
        for r in empty[:max(0, can_drop)]:
            out.append({"action": "shrink", "rid": r.rid})
        return out

    # -- apply ---------------------------------------------------------

    def run_once(self):
        """One control iteration: sense → decide → apply, plus the
        replica-seconds integral and finishing any quiesced shrinks.
        Never raises — the loop survives anything a replica or the
        chaos harness throws at it."""
        self.replica_seconds()
        try:
            decisions = self.evaluate()
        except Exception as e:  # mxlint: allow-broad-except(a sensing crash must not kill the control loop; next tick re-senses)
            _log.warning("autoscaler: evaluate failed: %s: %s",
                         type(e).__name__, e)
            decisions = []
        applied = []
        for d in decisions:
            if self._stop.is_set():
                # shutting down: drop the remaining decisions (and
                # their reservations) instead of racing the fleet's
                # teardown with fresh loads/spawns
                self._rollback(d)
                continue
            if self._apply_one(d):
                applied.append(d)
        self._finish_shrinks()
        return applied

    def _rollback(self, d):
        if d.get("action") == "load":
            self._unreserve(d["rid"], d["model"], loaded=False)
        self._release_spawn(d)

    def _release_spawn(self, d):
        """Return a planned spawn's ceiling slot — exactly once per
        decision (the flag pops), whether the spawn landed, failed,
        or the decision was dropped."""
        if d.pop("_spawn_reserved", None):
            with self._lock:
                self._spawns_pending = max(0, self._spawns_pending - 1)

    def _apply_one(self, d):
        """Apply one decision behind the ``serving.scale`` fault point;
        a fault (or any replica-side failure) drops the decision for
        this tick — its budget reservation rolls back and level-
        triggered re-evaluation retries it."""
        action = d["action"]
        what = f"{action}:{d.get('model') or d.get('rid')}"
        try:
            fault.inject("serving.scale", what)
            if action == "load":
                try:
                    self._do_load(d["model"], d["rid"],
                                  d.get("evict") or [])
                except BaseException:
                    self._unreserve(d["rid"], d["model"], loaded=False)
                    raise
                self._unreserve(d["rid"], d["model"], loaded=True)
                self._count("scale_up")
            elif action == "spawn_load":
                try:
                    r = self.fleet.spawn_one(models={})
                finally:
                    # landed or failed, the replica either counts as
                    # live now or never will — the ceiling slot frees
                    self._release_spawn(d)
                self.placer.register_replica(r.rid)
                self._count("spawn")
                if self._stop.is_set():
                    # stop() raced the (slow) spawn: the fleet may
                    # already have shut down, and a replica appended
                    # after its teardown snapshot would leak a live
                    # subprocess nothing will ever close
                    self.fleet.remove(r.rid, timeout=5.0)
                    self.placer.forget_replica(r.rid)
                    return False
                self._reserve(r.rid, d["model"],
                              self._policies[d["model"]].footprint())
                try:
                    self._do_load(d["model"], r.rid, [])
                except BaseException:
                    self._unreserve(r.rid, d["model"], loaded=False)
                    raise
                self._unreserve(r.rid, d["model"], loaded=True)
                self._count("scale_up")
            elif action == "unload":
                self.fleet.get(d["rid"]).admin("unload", d["model"])
                self.placer.record_unload(d["rid"], d["model"])
                self._count("scale_down")
            elif action == "shrink":
                r = self.fleet.get(d["rid"])
                r.begin_drain()
                with self._lock:
                    self._shrinking.setdefault(
                        d["rid"],
                        time.monotonic() + self.drain_s)
            else:
                raise ValueError(f"unknown scale action {action!r}")
            flightrec.record(flightrec.SCALING, "scale.apply",
                             action=action, model=d.get("model"),
                             rid=d.get("rid"))
            return True
        except fault.FaultInjected as e:
            self._rollback(d)
            self._count("faults")
            flightrec.record(flightrec.SCALING, "scale.dropped",
                             severity="warn", action=action,
                             model=d.get("model"), rid=d.get("rid"),
                             cause=type(e).__name__)
            _log.warning("autoscaler: %s dropped this tick (injected "
                         "fault: %s)", what, e)
            return False
        except Exception as e:  # mxlint: allow-broad-except(one failed decision must not kill the loop; re-derived next tick from live state)
            self._count("faults")
            flightrec.record(flightrec.SCALING, "scale.failed",
                             severity="warn", action=action,
                             model=d.get("model"), rid=d.get("rid"),
                             error=type(e).__name__)
            _log.warning("autoscaler: %s failed: %s: %s", what,
                         type(e).__name__, e)
            return False

    def _do_load(self, name, rid, evictions):
        p = self._policies[name]
        r = self.fleet.get(rid)
        for victim in evictions:
            r.admin("unload", victim)
            self.placer.record_unload(rid, victim)
            self._count("evict")
            with self._lock:
                self._evictions[victim] = (
                    self._evictions.get(victim, 0) + 1)
            vp = self._policies.get(victim)
            flightrec.record(flightrec.PLACEMENT, "placer.evict",
                             severity="warn", model=victim, rid=rid,
                             for_model=name,
                             tier=vp.slo.name if vp is not None
                             else None)
            _log.info("autoscaler: evicted %s from %s (LRU, making "
                      "room for %s)", victim, rid, name)
        r.admin("load", name, path=p.path, warmup=p.warmup,
                slo=p.slo.name)
        self.placer.record_load(rid, name, p.footprint())

    def _finish_shrinks(self):
        """Close draining replicas once quiesced (in-flight == 0, no
        active streams) or past the drain budget.  Sessions kept
        stepping while draining; the close snapshots them all
        synchronously, so migration onto a survivor is lossless —
        never a mid-stream kill."""
        with self._lock:
            pending = dict(self._shrinking)
        now = time.monotonic()
        for rid, deadline in pending.items():
            try:
                r = self.fleet.get(rid)
            except KeyError:
                with self._lock:
                    self._shrinking.pop(rid, None)
                self.placer.forget_replica(rid)
                continue
            quiesced = (r.inflight == 0 and r.active_streams() == 0)
            if not quiesced and now < deadline:
                continue
            try:
                self.fleet.remove(rid, timeout=self.drain_s)
            except Exception as e:  # mxlint: allow-broad-except(a replica that will not close cleanly is still removed from the books; its process dies with the fleet)
                _log.warning("autoscaler: shrink of %s: %s: %s", rid,
                             type(e).__name__, e)
            self.placer.forget_replica(rid)
            with self._lock:
                self._shrinking.pop(rid, None)
            self._count("shrink")

    def _count(self, key):
        with self._lock:
            self._counters[key] += 1

    # -- scale-from-zero (the router's on-demand path) -----------------

    def ensure_loaded(self, name, _retries=3):
        """Synchronous scale-from-zero: called by the router when a
        request names a managed model with no live copy.  Loads one
        copy (AOT path ⇒ sub-second), records the first-request
        latency gauge, and returns once the model is routable.  No
        budget anywhere and the fleet at its ceiling ⇒ typed
        :class:`~..error.ModelEvictedError` (503 + Retry-After)."""
        p = self._policies.get(name)
        if p is None:
            raise ModelNotFound(
                f"model {name!r} is not managed by the autoscaler")
        lock = self._demand_locks.setdefault(
            name, named_lock("autoscaler.demand"))
        with lock:
            if self.fleet.routable(name):
                return None        # raced another request: already up
            t0 = time.monotonic()
            # eviction-protection counts WITHOUT replica I/O: a full
            # desired() sweep would serialize one healthz round trip
            # per replica inside the live request path (one hung
            # replica = +10 s on the first request).  What protection
            # actually needs is "does this model have live traffic" —
            # placer residency + the router-side idle gauge answer
            # that from memory
            want = {
                m: (1 if (self.actual(m) > 0
                          and self._model_idle_s(m)
                          < self.idle_unload_s)
                    else pol.min_replicas)
                for m, pol in self._policies.items()}
            want[name] = max(1, want.get(name, 0))

            def place():
                # re-planned EVERY attempt against live state (a
                # replica chosen by a previous attempt may have died
                # or been shrunk meanwhile); the plan RESERVES its
                # budget under _plan_lock — the background loop
                # planning concurrently cannot hand the same free
                # bytes to another model — and any failure (including
                # the injected fault) rolls the reservation back
                # before the retry re-plans
                if self.fleet.routable(name):
                    return
                for _ in range(max(1, _retries)):
                    with self._plan_lock:
                        self._sync_placer()
                        plan = self._plan_grow(name, p, want)
                    if plan is None:
                        flightrec.record(
                            flightrec.PLACEMENT, "model.unplaceable",
                            severity="error", model=name,
                            max_replicas=self.max_replicas)
                        raise ModelEvictedError(
                            f"model {name!r} cannot be placed: every "
                            f"replica's HBM budget is held by busier "
                            f"models and the fleet is at its "
                            f"{self.max_replicas}-replica ceiling")
                    if plan["action"] != "wait_spawn":
                        break
                    # another caller's spawn holds the last ceiling
                    # slot.  BLOCK until it lands (a ~300 ms process
                    # spawn outlives this path's entire retry-backoff
                    # budget) and RE-PLAN in place: waiting on someone
                    # else's spawn must not consume one of this
                    # caller's fault-retry attempts, or a loser that
                    # then hits an injected transient is down to a
                    # thinner budget than a solo caller
                    deadline = time.monotonic() + self.drain_s
                    while time.monotonic() < deadline:
                        with self._lock:
                            pending = self._spawns_pending
                        if pending == 0:
                            break
                        time.sleep(0.02)
                else:
                    raise ReplicaUnavailableError(
                        f"a replica spawn was in flight with the fleet "
                        f"at its {self.max_replicas}-replica ceiling; "
                        f"retrying placement of {name!r}")
                rid = plan.get("rid")
                try:
                    fault.inject("serving.scale",
                                 f"on_demand:{name}")
                    if plan["action"] == "spawn_load":
                        try:
                            r = self.fleet.spawn_one(models={})
                        finally:
                            self._release_spawn(plan)
                        self.placer.register_replica(r.rid)
                        self._count("spawn")
                        if self._stop.is_set():
                            # stop() raced the (slow) spawn — same
                            # leak guard as _apply_one: a replica
                            # appended after the fleet's teardown
                            # snapshot would outlive it, and a retry
                            # against a stopping fleet cannot succeed
                            self.fleet.remove(r.rid, timeout=5.0)
                            self.placer.forget_replica(r.rid)
                            raise FleetDrainingError(
                                f"autoscaler stopped while spawning a "
                                f"replica for {name!r}")
                        rid = r.rid
                        self._reserve(rid, name, p.footprint())
                        self._do_load(name, rid, [])
                    else:
                        self._do_load(name, rid,
                                      plan.get("evict") or [])
                except KeyError as e:
                    self._release_spawn(plan)
                    if rid is not None:
                        self._unreserve(rid, name, loaded=False)
                    # the planned replica vanished between plan and
                    # place: typed + retryable (the next attempt
                    # re-plans), never a raw 500 to the live request
                    raise ReplicaUnavailableError(
                        f"replica vanished while placing {name!r}: "
                        f"{e}") from e
                except BaseException:
                    self._release_spawn(plan)
                    if rid is not None:
                        self._unreserve(rid, name, loaded=False)
                    raise
                self._unreserve(rid, name, loaded=True)

            # unlike the background loop, a dropped decision here
            # would fail a live request — retry injected transients
            # and vanished-replica races, but NOT the deterministic
            # no-capacity verdict (ModelEvictedError is a
            # ConnectionError for the router's 503 mapping, yet
            # re-planning it three times cannot change the answer)
            fault.retry(place, max_attempts=_retries, backoff=0.01,  # mxlint: allow-blocking-under-lock(the per-model demand lock exists precisely to serialize concurrent scale-from-zero requests through ONE load+retry; queued requests re-check routable() on entry and return immediately)
                        max_backoff=0.2,
                        retryable=(fault.TransientFault,
                                   ReplicaUnavailableError,
                                   ConnectionResetError,
                                   TimeoutError))
            ms = (time.monotonic() - t0) * 1000.0
            with self._lock:
                self._counters["scale_from_zero"] += 1
                self._scale_from_zero_ms[name] = round(ms, 3)
            self._count("scale_up")
            flightrec.record(flightrec.SCALING, "scale.from_zero",
                             model=name, ms=round(ms, 3))
            _log.info("autoscaler: scale-from-zero %s in %.0f ms",
                      name, ms)
            return ms

    # -- exposition ----------------------------------------------------

    def describe(self):
        """Desired-vs-actual per model + decision counters — rendered
        on the router's ``/metrics`` and under ``/healthz``
        ``"autoscale"`` (additive)."""
        desired = dict(self._last_desired)
        with self._lock:
            sfz = dict(self._scale_from_zero_ms)
        models = {}
        for name, p in self._policies.items():
            models[name] = {
                "desired": desired.get(name, p.min_replicas),
                "actual": self.actual(name),
                "slo": p.slo.name,
                "min_replicas": p.min_replicas,
                "scale_from_zero_ms": sfz.get(name),
            }
        with self._lock:
            counters = dict(self._counters)
            evictions = dict(self._evictions)
            shrinking = sorted(self._shrinking)
        return {
            "models": models,
            "decisions": counters,
            "evictions": evictions,
            "replicas": len(self._live_replicas()),
            "shrinking": shrinking,
            "replica_seconds": round(self.replica_seconds(), 3),
            "budget_bytes": self.placer.budget_bytes,
            "interval_s": self.interval_s,
            "idle_unload_s": self.idle_unload_s,
        }

    # -- loop ----------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(max(5.0, self.interval_s * 2))
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.run_once()
