"""Fleet router: health-checked, load-aware HTTP front end over N
replicas.

One replica dying (crash, stuck compile, reload) must cost the fleet
one replica's capacity, never an outage.  The router owns the request
side of that contract (:mod:`.fleet` owns the lifecycle side):

* **Load-aware routing** — every predict goes to the least-loaded
  *ready* replica (inflight gauge), shedding to the quietest queue
  before any 429.
* **Per-hop deadline budgets** — the request's deadline is split
  across its potential hops: with budget *B* and *a* attempts left,
  the next hop gets ``max(hop_min, B/a)``.  A slow first hop can never
  eat the whole budget and leave failover with nothing.
* **Bounded failover** — a hop that fails with a connection error,
  503, 429 or hop timeout retries on a *different* replica, up to
  ``MXNET_SERVING_FLEET_FAILOVERS`` extra hops.  400/404 never fail
  over (the request itself is wrong).
* **Hedged requests** — optionally (``MXNET_SERVING_FLEET_HEDGE_MS``)
  a second copy of a slow request is raced on another replica once the
  primary exceeds the hedge delay (fixed ms, or ``p95`` of observed
  hop latency); first answer wins.  Classic tail-at-scale medicine:
  one stalled replica stops defining the fleet's p99.
* **Fleet-aware admission** — no routable replica answers 503 with
  ``Retry-After`` (typed :class:`~..error.ReplicaUnavailableError`);
  a fully-draining fleet answers 503 via
  :class:`~..error.FleetDrainingError`.  Never a hang.
* **Zero-downtime rolls** — ``POST /v1/models/{name}:reload`` runs the
  fleet's rolling reload: replicas drain/reload/re-warm one at a time,
  ready capacity never below N-1.

``serving.route`` fires per routed request
(:func:`.admission.checked_route`); chaos specs for the ``fleet`` CI
stage land there, on ``serving.probe`` and on
``serving.replica_exec``.
"""
from __future__ import annotations

import json
import threading
import time

import numpy as onp

from ..base import get_env
from .. import fault
from ..error import FleetDrainingError, ReplicaUnavailableError
from .admission import (Admission, BadRequest, DeadlineExceeded,
                        QueueFullError, ServingError, ShuttingDown,
                        checked_route)
from .metrics import FleetMetrics, Histogram
from .server import JSONRequestHandler, ServingHTTPServer

__all__ = ["FleetRouter", "main"]


def _parse_hedge(raw):
    """``MXNET_SERVING_FLEET_HEDGE_MS`` -> None | 'p95' | float ms."""
    if raw is None:
        return None
    text = str(raw).strip().lower()
    if text in ("", "0", "off", "false"):
        return None
    if text == "p95":
        return "p95"
    ms = float(text)
    return ms if ms > 0 else None


class FleetRouter:
    """Route predicts across a :class:`~.fleet.ReplicaFleet`."""

    def __init__(self, fleet, host="127.0.0.1", port=0, metrics=None,
                 failovers=None, hedge=None, hop_min_ms=None,
                 deadline_ms=None):
        self.fleet = fleet
        self.metrics = metrics or FleetMetrics()
        self.metrics.attach_fleet(fleet)
        if fleet.metrics is None:
            # the prober records its failures into the router's metrics
            fleet.metrics = self.metrics
        self.metrics.register_with_profiler()
        self.admission = Admission(default_deadline_ms=deadline_ms)
        self.failovers = int(
            failovers if failovers is not None
            else get_env("MXNET_SERVING_FLEET_FAILOVERS", 2, int))
        if self.failovers < 0:
            raise ValueError(
                f"failovers must be >= 0, got {self.failovers}")
        self.hedge = _parse_hedge(
            hedge if hedge is not None
            else get_env("MXNET_SERVING_FLEET_HEDGE_MS", "0"))
        self.hop_min_ms = float(
            hop_min_ms if hop_min_ms is not None
            else get_env("MXNET_SERVING_FLEET_HOP_MIN_MS", 50.0, float))
        self._hop_ms = Histogram()   # successful-hop latencies (p95)
        self.host = host
        self.port = int(port)
        self.t_start = time.monotonic()
        self._httpd = None
        self._thread = None

    # -- routing core (in-process API; the HTTP handler wraps it) -----

    def route(self, name, inputs, deadline_ms=None, inputs_json=None):
        """Route one predict; returns ``(outputs, timing)`` where
        outputs is the replica's leaf list.  ``inputs`` is the tuple of
        instance arrays; ``inputs_json`` optionally carries the
        pre-encoded JSON tensor list so process-backend hops (and
        their failover/hedge resends) do not re-serialize."""
        t0 = time.monotonic()
        code = 500
        try:
            result = self._route(name, inputs, deadline_ms,
                                 inputs_json, t0)
            code = 200
            return result
        except ServingError as e:
            code = e.http_status
            raise
        except (FleetDrainingError, ConnectionError):
            code = 503
            raise
        finally:
            self.metrics.record_route(
                code, (time.monotonic() - t0) * 1000.0)

    def _route(self, name, inputs, deadline_ms, inputs_json, t0):
        checked_route(name)
        deadline = self.admission.deadline_ms(deadline_ms)
        t_end = t0 + deadline / 1000.0
        attempts = 1 + self.failovers
        tried: set = set()
        last = None
        for k in range(attempts):
            r = self.fleet.pick(exclude=tried)
            if r is None:
                if self.fleet.all_draining():
                    raise FleetDrainingError(
                        "fleet is draining, not accepting work")
                if last is not None:
                    raise last
                raise ReplicaUnavailableError(
                    f"no ready replica for {name!r} "
                    f"({len(self.fleet.replicas)} known)")
            if k > 0:
                self.metrics.record_failover()
            remaining_ms = (t_end - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                raise DeadlineExceeded(
                    f"fleet deadline spent after {k} hop(s) for "
                    f"{name!r}", queue_ms=deadline)
            hop_ms = min(remaining_ms,
                         max(self.hop_min_ms,
                             remaining_ms / (attempts - k)))
            try:
                return self._attempt(r, name, inputs, hop_ms,
                                     inputs_json)
            except QueueFullError as e:
                # overload, not ill health: shed to another replica
                # before surfacing 429
                tried.add(r.rid)
                last = e
            except (ShuttingDown, DeadlineExceeded,
                    ConnectionError) as e:
                # 503 / hop timeout / refused socket (includes
                # injected TransientFault): failover.  The passive
                # health note happened inside _call, attributed to
                # whichever replica actually failed (under hedging
                # that may not be ``r``).
                tried.add(r.rid)
                last = e
        raise last

    def _call(self, r, name, inputs, hop_ms, inputs_json):
        """One physical hop, with the passive-health note attributed
        HERE — the only place the per-replica outcome is known.  With
        hedging on, the winner's success must not be credited to a
        stalled primary (that would reset its failure budget and keep
        it routable forever); the stalled hop notes its own failure
        when its hop deadline resolves it, even after the race moved
        on."""
        t0 = time.monotonic()
        try:
            out = r.predict(name, inputs, deadline_ms=hop_ms,
                            inputs_json=inputs_json)
        except QueueFullError:
            raise              # overload is load, not ill health
        except (ShuttingDown, DeadlineExceeded, ConnectionError):
            r.note_failure()
            raise
        r.note_success()
        self._hop_ms.observe((time.monotonic() - t0) * 1000.0)
        return out

    def _hedge_delay_ms(self):
        if self.hedge is None:
            return None
        if self.hedge == "p95":
            # adapt only once there is a latency distribution to trust
            if self._hop_ms.snapshot()["count"] < 20:
                return None
            return max(1.0, self._hop_ms.quantile(0.95))
        return float(self.hedge)

    def _attempt(self, r, name, inputs, hop_ms, inputs_json):
        """One hop, optionally hedged: if the primary replica has not
        answered within the hedge delay, race a second copy on another
        replica and take whichever answers first."""
        hedge_ms = self._hedge_delay_ms()
        if hedge_ms is None or hedge_ms >= hop_ms:
            return self._call(r, name, inputs, hop_ms, inputs_json)
        cond = threading.Condition()
        slots: dict = {}
        order: list = []

        def run(which, rep, budget_ms):
            try:
                res = ("ok", self._call(rep, name, inputs, budget_ms,
                                        inputs_json))
            except BaseException as e:  # mxlint: allow-broad-except(delivered through the race slot and re-raised on the routing thread)
                res = ("err", e)
            with cond:
                slots[which] = res
                order.append(which)
                cond.notify_all()

        threading.Thread(target=run, args=("primary", r, hop_ms),
                         name=f"hop-{r.rid}", daemon=True).start()
        with cond:
            cond.wait_for(lambda: "primary" in slots,
                          hedge_ms / 1000.0)
            if "primary" in slots:
                kind, val = slots["primary"]
                if kind == "err":
                    raise val
                return val
        r2 = self.fleet.pick(exclude={r.rid})
        if r2 is None or r2 is r:
            # nowhere to hedge: wait the primary out
            with cond:
                if not cond.wait_for(lambda: "primary" in slots,
                                     hop_ms / 1000.0 + 2.0):
                    raise DeadlineExceeded(
                        f"hop to {r.rid} exceeded its "
                        f"{hop_ms:.0f}ms budget", queue_ms=hop_ms)
                kind, val = slots["primary"]
            if kind == "err":
                raise val
            return val
        self.metrics.record_hedge(won=False)   # launched
        threading.Thread(target=run, args=("hedge", r2, hop_ms),
                         name=f"hedge-{r2.rid}", daemon=True).start()
        with cond:
            done = cond.wait_for(
                lambda: any(v[0] == "ok" for v in slots.values())
                or len(slots) == 2,
                hop_ms / 1000.0 + 2.0)
            winners = [w for w in order if slots[w][0] == "ok"]
            if winners:
                if winners[0] == "hedge":
                    self.metrics.record_hedge(won=True)
                return slots[winners[0]][1]
            if not done:
                raise DeadlineExceeded(
                    f"hedged hop to {r.rid}/{r2.rid} exceeded its "
                    f"{hop_ms:.0f}ms budget", queue_ms=hop_ms)
            # both failed: surface the primary's error (arrival order
            # is race noise; the primary's cause is the actionable one)
            raise slots.get("primary", slots[order[0]])[1]

    # -- fleet health view --------------------------------------------

    def health(self):
        """``(code, body)`` for the router's ``/healthz``: fleet-level
        status + the per-replica state machine."""
        states = self.fleet.states()
        ready = sum(1 for st in states.values()
                    if st["state"] == "ready" and st["healthy"])
        if self.fleet.all_draining():
            status = "draining"
        elif ready == 0:
            status = "unavailable"
        elif ready < len(states):
            # anything short of full strength — including dead
            # replicas that will never return — is an operator signal
            status = "degraded"
        else:
            status = "ok"
        body = {
            "status": status,
            "uptime_s": round(time.monotonic() - self.t_start, 3),
            "ready": ready,
            "replicas": states,
            "models": sorted(self.fleet.models),
        }
        return (200 if ready else 503), body

    # -- HTTP front end -----------------------------------------------

    def start(self):
        self._httpd = ServingHTTPServer((self.host, self.port),
                                        _RouterHandler)
        self._httpd.app = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router-http",
            daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self, drain=True, timeout=30.0):
        """Stop routing; with ``drain`` also drain + close the fleet
        (replicas finish in-flight work first)."""
        if drain:
            self.fleet.shutdown(timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.metrics.unregister_from_profiler()


class _RouterHandler(JSONRequestHandler):

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            code, body = self.app.health()
            return self._send(code, body)
        if path == "/metrics":
            return self._send(200, self.app.metrics.render().encode(),
                              content_type="text/plain; version=0.0.4")
        self._send(404, {"error": "NotFound", "message": path})

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/models/") and ":" in path:
            name, _, verb = path[len("/v1/models/"):].rpartition(":")
            handler = {"predict": self._predict,
                       "reload": self._reload,
                       "load": self._load,
                       "unload": self._unload}.get(verb)
            if handler is not None and name:
                return handler(name)
        self._send(404, {"error": "NotFound", "message": path})

    def _guarded(self, fn):
        """Map the typed routing errors onto HTTP, with Retry-After on
        every retryable condition."""
        try:
            return fn()
        except ServingError as e:
            hdrs = ({"Retry-After": "1"}
                    if e.http_status in (429, 503) else None)
            self._send(e.http_status, e.payload(), extra_headers=hdrs)
        except FleetDrainingError as e:
            self._send(503, {"error": "FleetDrainingError",
                             "message": str(e)},
                       extra_headers={"Retry-After": "1"})
        except fault.TransientFault as e:
            self._send(503, {"error": "TransientFault",
                             "message": str(e)},
                       extra_headers={"Retry-After": "1"})
        except ConnectionError as e:
            # ReplicaUnavailableError and raw refused sockets: the
            # condition clears when a replica re-warms
            self._send(503, {"error": type(e).__name__,
                             "message": str(e)},
                       extra_headers={"Retry-After": "1"})
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            self._send(500, {"error": type(e).__name__,
                             "message": str(e)})

    def _predict(self, name):
        def fn():
            specs = self.app.fleet.model_meta(name)
            body = self._body()
            if "inputs" not in body or not isinstance(body["inputs"],
                                                      list):
                raise BadRequest('body needs "inputs": [tensor, ...]')
            if len(body["inputs"]) != len(specs):
                raise BadRequest(
                    f"model {name!r} takes {len(specs)} inputs, got "
                    f"{len(body['inputs'])}")
            try:
                arrs = tuple(onp.asarray(x, dtype=spec["dtype"])
                             for x, spec in zip(body["inputs"], specs))
            except (TypeError, ValueError) as e:
                raise BadRequest(f"malformed input tensor: {e}")
            for a, spec in zip(arrs, specs):
                want = tuple(spec["shape"][1:])
                if tuple(a.shape) != want:
                    raise BadRequest(
                        f"instance shape {tuple(a.shape)} != exported "
                        f"instance shape {want}")
            outputs, timing = self.app.route(
                name, arrs, deadline_ms=body.get("timeout_ms"),
                inputs_json=json.dumps(body["inputs"]))
            self._send(200, {
                "outputs": [o if isinstance(o, list)
                            else onp.asarray(o).tolist()
                            for o in outputs],
                "timing": {k: round(v, 3)
                           for k, v in (timing or {}).items()
                           if v is not None}})
        self._guarded(fn)

    def _reload(self, name):
        def fn():
            body = self._body()
            report = self.app.fleet.rolling_reload(
                name, path=body.get("path"),
                version=body.get("version"))
            self._send(200, report)
        self._guarded(fn)

    def _load(self, name):
        def fn():
            body = self._body()
            if "path" not in body:
                raise BadRequest('load needs {"path": artifact-prefix}')
            self._send(200, self.app.fleet.load_everywhere(
                name, body["path"], version=body.get("version"),
                warmup=body.get("warmup")))
        self._guarded(fn)

    def _unload(self, name):
        def fn():
            self._send(200, self.app.fleet.unload_everywhere(name))
        self._guarded(fn)


def main(argv=None):
    import argparse
    import signal

    from .fleet import ReplicaFleet

    p = argparse.ArgumentParser(
        description="mxnet-tpu multi-replica serving fleet router")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PREFIX",
                   help="serve artifact PREFIX as model NAME on every "
                        "replica")
    p.add_argument("--replicas", type=int,
                   default=get_env("MXNET_SERVING_FLEET_REPLICAS", 2,
                                   int))
    p.add_argument("--backend", choices=("thread", "process"),
                   default="process",
                   help="replica isolation (process = one server "
                        "subprocess per replica)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int,
                   default=get_env("MXNET_SERVING_PORT", 8080, int))
    p.add_argument("--no-warmup", action="store_true")
    args = p.parse_args(argv)

    models = {}
    for spec in args.model:
        name, sep, path = spec.partition("=")
        if not sep:
            p.error(f"--model wants NAME=PREFIX, got {spec!r}")
        models[name] = path
    if not models:
        p.error("need at least one --model NAME=PREFIX")

    fleet = ReplicaFleet(models, n=args.replicas, backend=args.backend,
                         warmup=not args.no_warmup)
    print(f"[fleet] spawning {args.replicas} {args.backend} "
          f"replica(s)", flush=True)
    fleet.spawn()
    router = FleetRouter(fleet, host=args.host, port=args.port)
    port = router.start()
    print(f"[fleet] routing on {args.host}:{port} over "
          f"{fleet.ready_count()} ready replica(s)", flush=True)

    done = threading.Event()

    def stop(signum, frame):
        print(f"[fleet] signal {signum}: draining fleet", flush=True)
        done.set()

    signal.signal(signal.SIGTERM, stop)
    signal.signal(signal.SIGINT, stop)
    done.wait()
    router.shutdown(drain=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
