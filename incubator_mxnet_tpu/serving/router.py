"""Fleet router: health-checked, load-aware HTTP front end over N
replicas.

One replica dying (crash, stuck compile, reload) must cost the fleet
one replica's capacity, never an outage.  The router owns the request
side of that contract (:mod:`.fleet` owns the lifecycle side):

* **Load-aware routing** — every predict goes to the least-loaded
  *ready* replica (inflight gauge), shedding to the quietest queue
  before any 429.
* **Per-hop deadline budgets** — the request's deadline is split
  across its potential hops: with budget *B* and *a* attempts left,
  the next hop gets ``max(hop_min, B/a)``.  A slow first hop can never
  eat the whole budget and leave failover with nothing.
* **Bounded failover** — a hop that fails with a connection error,
  503, 429 or hop timeout retries on a *different* replica, up to
  ``MXNET_SERVING_FLEET_FAILOVERS`` extra hops.  400/404 never fail
  over (the request itself is wrong).
* **Hedged requests** — optionally (``MXNET_SERVING_FLEET_HEDGE_MS``)
  a second copy of a slow request is raced on another replica once the
  primary exceeds the hedge delay (fixed ms, or ``p95`` of observed
  hop latency); first answer wins.  Classic tail-at-scale medicine:
  one stalled replica stops defining the fleet's p99.
* **Fleet-aware admission** — no routable replica answers 503 with
  ``Retry-After`` (typed :class:`~..error.ReplicaUnavailableError`);
  a fully-draining fleet answers 503 via
  :class:`~..error.FleetDrainingError`.  Never a hang.
* **Zero-downtime rolls** — ``POST /v1/models/{name}:reload`` runs the
  fleet's rolling reload: replicas drain/reload/re-warm one at a time,
  ready capacity never below N-1.

``serving.route`` fires per routed request
(:func:`.admission.checked_route`); chaos specs for the ``fleet`` CI
stage land there, on ``serving.probe`` and on
``serving.replica_exec``.
"""
from __future__ import annotations

import contextvars
import json
import threading
import time

import numpy as onp

from ..base import get_env
from .. import fault, flightrec, trace
from ..error import (FleetDrainingError, ReplicaUnavailableError,
                     RouterForwardError, RouterLeaseError,
                     SessionExpiredError, SessionLostError)
from ..locks import named_condition, named_lock
from .admission import (Admission, BadRequest, ClientDisconnected,
                        DeadlineExceeded, ModelNotFound, QueueFullError,
                        ServingError, ShuttingDown, checked_route,
                        retry_after_s)
from .metrics import FleetMetrics, Histogram
from .server import JSONRequestHandler, ServingHTTPServer
from .sessions import SessionNotFound
from . import routerha

__all__ = ["FleetRouter", "main"]


def _parse_hedge(raw):
    """``MXNET_SERVING_FLEET_HEDGE_MS`` -> None | 'p95' | float ms."""
    if raw is None:
        return None
    text = str(raw).strip().lower()
    if text in ("", "0", "off", "false"):
        return None
    if text == "p95":
        return "p95"
    ms = float(text)
    return ms if ms > 0 else None


class FleetRouter:
    """Route predicts across a :class:`~.fleet.ReplicaFleet`."""

    def __init__(self, fleet, host="127.0.0.1", port=0, metrics=None,
                 failovers=None, hedge=None, hop_min_ms=None,
                 deadline_ms=None, ha=None, router_id=None,
                 ha_dir=None, lease_ttl_s=None):
        self.fleet = fleet
        self.metrics = metrics or FleetMetrics()
        self.metrics.attach_fleet(fleet)
        if fleet.metrics is None:
            # the prober records its failures into the router's metrics
            fleet.metrics = self.metrics
        self.metrics.register_with_profiler()
        self.admission = Admission(default_deadline_ms=deadline_ms)
        self.failovers = int(
            failovers if failovers is not None
            else get_env("MXNET_SERVING_FLEET_FAILOVERS", 2, int))
        if self.failovers < 0:
            raise ValueError(
                f"failovers must be >= 0, got {self.failovers}")
        self.hedge = _parse_hedge(
            hedge if hedge is not None
            else get_env("MXNET_SERVING_FLEET_HEDGE_MS", "0"))
        self.hop_min_ms = float(
            hop_min_ms if hop_min_ms is not None
            else get_env("MXNET_SERVING_FLEET_HOP_MIN_MS", 50.0, float))
        self._hop_ms = Histogram()   # successful-hop latencies (p95)
        # the autoscaling control plane attaches itself here
        # (Autoscaler.__init__): routing then consults it for models
        # currently scaled to zero (on-demand reload) and /healthz
        # gains the additive desired-vs-actual view
        self.autoscaler = None
        # session affinity: a session's carry lives on exactly ONE
        # replica; the router remembers which (sid -> (model, rid))
        # and re-homes it from its snapshot when that replica dies
        self._session_homes: dict = {}
        self._session_lock = named_lock("router.sessions")
        self.metrics.attach_session_count(
            lambda: len(self._session_homes))
        self.host = host
        self.port = int(port)
        # router high availability (docs/serving.md "Router high
        # availability"): OFF unless explicitly configured — a bare
        # single-router deployment starts no HA thread, publishes no
        # lease, and keeps its pinned healthz/describe shapes
        self.ha = None
        if ha is None:
            ha = routerha.from_env(router_id=router_id, ha_dir=ha_dir,
                                   lease_ttl_s=lease_ttl_s)
        if ha is not None:
            ha.attach(self)     # sets self.ha + fleet.membership
        self.t_start = time.monotonic()
        self._httpd = None
        self._thread = None

    def _known_model(self, name):
        """True when ``name`` is in the fleet's catalog (models,
        session models, or autoscaler-managed).  Per-model metrics
        only label KNOWN names — arbitrary client-supplied names must
        not grow the registry (unbounded label cardinality; the PR 3
        hardening, kept)."""
        if name in self.fleet.models or name in self.fleet.session_models:
            return True
        return (self.autoscaler is not None
                and self.autoscaler.manages(name))

    def _retry_headers(self):
        """Live ``Retry-After``: with nothing routable, the time the
        prober needs to readmit a replica; under load, the time the
        current inflight queue needs to flush at the observed p50."""
        if not self.fleet.routable():
            probe_s = (self.fleet._probe_ms / 1000.0
                       * max(1, self.fleet._probe_fails or 1))
            return {"Retry-After": str(max(1, min(30,
                                                  int(probe_s + 1))))}
        inflight = sum(st["inflight"]
                       for st in self.fleet.states().values())
        p50 = self._hop_ms.quantile(0.5)
        return {"Retry-After": retry_after_s(inflight + 1,
                                             p50 or None)}

    # -- routing core (in-process API; the HTTP handler wraps it) -----

    def route(self, name, inputs, deadline_ms=None, inputs_json=None,
              live=None):
        """Route one predict; returns ``(outputs, timing)`` where
        outputs is the replica's leaf list.  ``inputs`` is the tuple of
        instance arrays; ``inputs_json`` optionally carries the
        pre-encoded JSON tensor list so process-backend hops (and
        their failover/hedge resends) do not re-serialize.  ``live``
        is an optional ``() -> bool`` client-liveness probe checked
        between hops: a disconnected client's request is abandoned
        (typed, counted) instead of burning failover hops for a socket
        nobody reads."""
        t0 = time.monotonic()
        code = 500
        label = name if self._known_model(name) else None
        if label is not None:
            self.metrics.note_model_inflight(label, +1)
        # a trace is born at the front end: when the HTTP handler (or
        # any caller) already activated one, ride it; otherwise the
        # in-process route() API IS the front end and makes the head-
        # sampling decision itself (None when sampling is off — one
        # contextvar read + one float compare)
        root = (trace.start_trace("router.request", model=name)
                if trace.current_span() is None else None)
        try:
            with trace.activate(root):
                result = self._route(name, inputs, deadline_ms,
                                     inputs_json, t0, live)
            code = 200
            return result
        except ServingError as e:
            code = e.http_status
            if code >= 500:
                # a typed framework error is crossing the router's
                # top-level boundary: the black box writes its crash
                # dump HERE (rate-limited, best-effort — the typed
                # error below surfaces untouched)
                flightrec.note_error("router", e)
            raise
        except (FleetDrainingError, ConnectionError) as e:
            code = 503
            flightrec.note_error("router", e)
            raise
        except Exception as e:  # mxlint: allow-broad-except(recorded in the flight ring and re-raised unchanged — the surfacing 500 stays the original error)
            flightrec.note_error("router", e)
            raise
        finally:
            if root is not None:
                root.set(code=code)
                root.finish(
                    outcome="ok" if code == 200 else f"http_{code}")
            if label is not None:
                self.metrics.note_model_inflight(label, -1)
            self.metrics.record_route(
                code, (time.monotonic() - t0) * 1000.0, model=label,
                trace_id=(root.trace_id if root is not None
                          else trace.current_trace_id()))

    def _route(self, name, inputs, deadline_ms, inputs_json, t0,
               live=None):
        checked_route(name)
        deadline = self.admission.deadline_ms(deadline_ms)
        t_end = t0 + deadline / 1000.0
        attempts = 1 + self.failovers
        tried: set = set()
        last = None
        for k in range(attempts):
            if live is not None and not live():
                self.metrics.record_route_cancel()
                raise ClientDisconnected(
                    f"client of {name!r} disconnected after {k} "
                    "hop(s)")
            r = self.fleet.pick(exclude=tried, name=name)
            if r is None and self.autoscaler is not None \
                    and self.autoscaler.manages(name):
                # scale-from-zero: the model was idle-unloaded (or
                # evicted); this request pays the (AOT-cheap) reload
                # instead of a 404/503.  Span AND flight event — the
                # latency is attributable even with tracing off
                t_sfz = time.monotonic()
                with trace.span("router.scale_from_zero", model=name):
                    self.autoscaler.ensure_loaded(name)
                flightrec.record(
                    flightrec.SCALING, "router.scale_from_zero",
                    model=name,
                    ms=round((time.monotonic() - t_sfz) * 1e3, 3))
                r = self.fleet.pick(exclude=tried, name=name)
            if r is None:
                if self.fleet.all_draining():
                    raise FleetDrainingError(
                        "fleet is draining, not accepting work")
                if last is not None:
                    raise last
                raise ReplicaUnavailableError(
                    f"no ready replica for {name!r} "
                    f"({len(self.fleet.replicas)} known)")
            if k > 0:
                self.metrics.record_failover()
                # the retry hop that follows is its own span; this
                # event marks WHY it exists (the previous hop's typed
                # failure is that hop span's outcome)
                cause = (type(last).__name__ if last is not None
                         else None)
                trace.add_event("router.failover", attempt=k,
                                model=name, cause=cause)
                flightrec.record(flightrec.HEALTH, "router.failover",
                                 severity="warn", attempt=k,
                                 model=name, cause=cause)
            remaining_ms = (t_end - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                raise DeadlineExceeded(
                    f"fleet deadline spent after {k} hop(s) for "
                    f"{name!r}", queue_ms=deadline)
            hop_ms = min(remaining_ms,
                         max(self.hop_min_ms,
                             remaining_ms / (attempts - k)))
            try:
                return self._attempt(r, name, inputs, hop_ms,
                                     inputs_json)
            except QueueFullError as e:
                # overload, not ill health: shed to another replica
                # before surfacing 429
                tried.add(r.rid)
                last = e
            except ModelNotFound as e:
                # the autoscaler unloaded/evicted the model from THIS
                # replica between pick and execute: another holder (or
                # the on-demand reload path) may still serve it — only
                # when no replica is left does the 404 surface
                tried.add(r.rid)
                last = e
            except (ShuttingDown, DeadlineExceeded,
                    ConnectionError) as e:
                # 503 / hop timeout / refused socket (includes
                # injected TransientFault): failover.  The passive
                # health note happened inside _call, attributed to
                # whichever replica actually failed (under hedging
                # that may not be ``r``).
                tried.add(r.rid)
                last = e
        raise last

    def _call(self, r, name, inputs, hop_ms, inputs_json, kind="hop"):
        """One physical hop, with the passive-health note attributed
        HERE — the only place the per-replica outcome is known.  With
        hedging on, the winner's success must not be credited to a
        stalled primary (that would reset its failure budget and keep
        it routable forever); the stalled hop notes its own failure
        when its hop deadline resolves it, even after the race moved
        on.

        Every physical attempt is its own trace span
        (``router.hop`` / ``router.hedge``), finishing with the typed
        outcome — a chaos timeline shows each failed hop AND the hop
        that recovered.  The span is the active context for the hop,
        so a process replica's header and a thread replica's batcher
        spans both parent onto it."""
        t0 = time.monotonic()
        with trace.span(f"router.{kind}", replica=r.rid, model=name,
                        budget_ms=round(hop_ms, 1)):
            try:
                out = r.predict(name, inputs, deadline_ms=hop_ms,
                                inputs_json=inputs_json)
            except QueueFullError:
                raise          # overload is load, not ill health
            except (ShuttingDown, DeadlineExceeded,
                    ConnectionError) as e:
                # the typed failed hop, in the black box: with
                # tracing off (the common case) this is the record a
                # postmortem hangs the failover story on
                flightrec.record(flightrec.HEALTH, "router.hop_failed",
                                 severity="warn", replica=r.rid,
                                 model=name, kind=kind,
                                 error=type(e).__name__)
                r.note_failure()
                raise
        r.note_success()
        self._hop_ms.observe((time.monotonic() - t0) * 1000.0)
        return out

    def _hedge_delay_ms(self):
        if self.hedge is None:
            return None
        if self.hedge == "p95":
            # adapt only once there is a latency distribution to trust
            if self._hop_ms.snapshot()["count"] < 20:
                return None
            return max(1.0, self._hop_ms.quantile(0.95))
        return float(self.hedge)

    def _attempt(self, r, name, inputs, hop_ms, inputs_json):
        """One hop, optionally hedged: if the primary replica has not
        answered within the hedge delay, race a second copy on another
        replica and take whichever answers first."""
        hedge_ms = self._hedge_delay_ms()
        if hedge_ms is None or hedge_ms >= hop_ms:
            return self._call(r, name, inputs, hop_ms, inputs_json)
        cond = named_condition("router.hedge")
        slots: dict = {}
        order: list = []

        def run(which, rep, budget_ms, ctx):
            # ctx is a per-thread contextvars copy taken on the
            # routing thread: the hop span parents onto the request
            # span even though the race runs off-thread (each thread
            # gets its OWN copy — a single Context cannot be entered
            # by two OS threads)
            try:
                res = ("ok", ctx.run(
                    self._call, rep, name, inputs, budget_ms,
                    inputs_json, "hedge" if which == "hedge"
                    else "hop"))
            except BaseException as e:  # mxlint: allow-broad-except(delivered through the race slot and re-raised on the routing thread)
                res = ("err", e)
            with cond:
                slots[which] = res
                order.append(which)
                cond.notify_all()

        threading.Thread(target=run,
                         args=("primary", r, hop_ms,
                               contextvars.copy_context()),
                         name=f"hop-{r.rid}", daemon=True).start()
        with cond:
            cond.wait_for(lambda: "primary" in slots,
                          hedge_ms / 1000.0)
            if "primary" in slots:
                kind, val = slots["primary"]
                if kind == "err":
                    raise val
                return val
        r2 = self.fleet.pick(exclude={r.rid}, name=name)
        if r2 is None or r2 is r:
            # nowhere to hedge: wait the primary out
            with cond:
                if not cond.wait_for(lambda: "primary" in slots,
                                     hop_ms / 1000.0 + 2.0):
                    raise DeadlineExceeded(
                        f"hop to {r.rid} exceeded its "
                        f"{hop_ms:.0f}ms budget", queue_ms=hop_ms)
                kind, val = slots["primary"]
            if kind == "err":
                raise val
            return val
        self.metrics.record_hedge(won=False)   # launched
        trace.add_event("router.hedge_launched", replica=r2.rid,
                        primary=r.rid, after_ms=round(hedge_ms, 1))
        flightrec.record(flightrec.HEALTH, "router.hedge_launched",
                         replica=r2.rid, primary=r.rid,
                         after_ms=round(hedge_ms, 1))
        threading.Thread(target=run,
                         args=("hedge", r2, hop_ms,
                               contextvars.copy_context()),
                         name=f"hedge-{r2.rid}", daemon=True).start()
        with cond:
            done = cond.wait_for(
                lambda: any(v[0] == "ok" for v in slots.values())
                or len(slots) == 2,
                hop_ms / 1000.0 + 2.0)
            winners = [w for w in order if slots[w][0] == "ok"]
            if winners:
                if winners[0] == "hedge":
                    self.metrics.record_hedge(won=True)
                    trace.add_event("router.hedge_won",
                                    replica=r2.rid, primary=r.rid)
                    flightrec.record(flightrec.HEALTH,
                                     "router.hedge_won",
                                     replica=r2.rid, primary=r.rid)
                return slots[winners[0]][1]
            if not done:
                raise DeadlineExceeded(
                    f"hedged hop to {r.rid}/{r2.rid} exceeded its "
                    f"{hop_ms:.0f}ms budget", queue_ms=hop_ms)
            # both failed: surface the primary's error (arrival order
            # is race noise; the primary's cause is the actionable one)
            raise slots.get("primary", slots[order[0]])[1]

    def model_meta(self, name):
        """Input specs for ``name`` — like ``fleet.model_meta`` but
        autoscale-aware: a managed model currently scaled to zero is
        reloaded on demand instead of 404ing its first request."""
        try:
            return self.fleet.model_meta(name)
        except ModelNotFound:
            if (self.autoscaler is not None
                    and self.autoscaler.manages(name)):
                self.autoscaler.ensure_loaded(name)
                return self.fleet.model_meta(name)
            raise

    # -- stateful sessions: affinity + the failover contract ----------
    #
    # A session's carry lives on exactly one replica.  On replica
    # death or drain the router either MIGRATES the session — a
    # surviving replica adopts it from its latest CRC'd snapshot, and
    # the resumed continuation is bitwise-equal to an unbroken run
    # from that snapshot — or fails with typed SessionLostError.
    # Never a hang, never a stream that silently restarts from
    # scratch (docs/serving.md "Sessions").

    def session_create(self, model, sid=None):
        code = 500
        t0 = time.monotonic()
        try:
            checked_route(model)
            r = self.fleet.pick()
            if r is None:
                if self.fleet.all_draining():
                    raise FleetDrainingError(
                        "fleet is draining, not accepting sessions")
                raise ReplicaUnavailableError(
                    f"no ready replica to host a {model!r} session")
            info = r.session_create(model, sid)
            with self._session_lock:
                self._session_homes[info["session_id"]] = (model,
                                                           r.rid)
            info["replica"] = r.rid
            self._ha_publish()   # peers' owner_of() must see it
            code = 200
            return info
        except ServingError as e:
            code = e.http_status
            raise
        except (FleetDrainingError, ConnectionError):
            code = 503
            raise
        finally:
            self.metrics.record_route(
                code, (time.monotonic() - t0) * 1000.0,
                model=model if self._known_model(model) else None,
                trace_id=trace.current_trace_id())

    def _session_home(self, model, sid):
        with self._session_lock:
            entry = self._session_homes.get(sid)
        if entry is None or entry[0] != model:
            raise SessionNotFound(
                f"no session {sid!r} for model {model!r} on this "
                "fleet")
        return entry[1]

    def _ha_publish(self):
        """Push the session registry to the HA store now (best
        effort — the periodic beat re-publishes anyway)."""
        if self.ha is not None:
            try:
                self.ha.beat_once()
            except RouterLeaseError:
                pass   # counted in the HA block; next beat retries

    def _adopt_orphan(self, model, sid):
        """Takeover (called by :class:`~.routerha.RouterHA`): adopt a
        dead peer router's session affinity.  The replica-side restore
        happens lazily on the next step through the normal
        migrate-from-snapshot path — ``record_migration`` fires, the
        ``session_steps`` re-base stays visible, chunks already
        delivered are never re-sent."""
        with self._session_lock:
            if sid not in self._session_homes:
                self._session_homes[sid] = (model, None)

    def session_step(self, model, sid, inputs, steps=1,
                     deadline_ms=None, on_chunk=None):
        code = 500
        t0 = time.monotonic()
        try:
            result = self._session_step(model, sid, inputs, steps,
                                        deadline_ms, on_chunk)
            code = 200
            return result
        except (SessionExpiredError, SessionLostError) as e:
            # terminal for this id either way: drop the affinity entry
            # so churned/expired sessions never accumulate in the
            # router's map (and the fleet sessions gauge stays honest)
            code = 410
            if isinstance(e, SessionLostError):
                # loss (vs policy expiry) is a crash-class incident:
                # the black box dumps the history that led here
                flightrec.note_error("router", e)
            with self._session_lock:
                self._session_homes.pop(sid, None)
            raise
        except ServingError as e:
            code = e.http_status
            if code >= 500:
                flightrec.note_error("router", e)
            raise
        except (FleetDrainingError, ConnectionError) as e:
            code = 503
            flightrec.note_error("router", e)
            raise
        finally:
            self.metrics.record_route(
                code, (time.monotonic() - t0) * 1000.0,
                model=model if self._known_model(model) else None,
                trace_id=trace.current_trace_id())

    def _session_step(self, model, sid, inputs, steps, deadline_ms,
                      on_chunk):
        checked_route(model)
        deadline = self.admission.deadline_ms(deadline_ms)
        try:
            rid = self._session_home(model, sid)
        except SessionNotFound:
            # HA: the sid may belong to a dead peer router whose lease
            # just expired and whose ring-share hashes to us — claim it
            # (sweeps + adopts) before giving up with a 404
            if self.ha is None or self.ha.claim_orphan(sid) != model:
                raise
            rid = self._session_home(model, sid)
        chunks_out = [0]
        if on_chunk is not None:
            user_cb = on_chunk

            def on_chunk(chunk):
                chunks_out[0] += 1
                user_cb(chunk)
        if rid is None:
            # takeover-adopted orphan: no local owner replica yet —
            # restore from the latest durable snapshot through the
            # normal migrate path (empty exclude set: any routable
            # replica may adopt)
            return self._migrate_step(model, sid, set(), inputs, steps,
                                      deadline, on_chunk, chunks_out,
                                      None)
        try:
            r = self.fleet.get(rid)
        except KeyError:
            r = None
        last = None
        from .fleet import DEAD
        if r is not None and r.state != DEAD:
            # retry the OWNER first: a transient hop fault (injected
            # serving.replica_exec fires before any state moves, a
            # refused connect moves none) must not trigger a spurious
            # migration that re-bases onto an older snapshot
            outcome, value = self._try_step(r, model, sid, inputs,
                                            steps, deadline, on_chunk,
                                            chunks_out)
            if outcome == "ok":
                return value
            last = value
        return self._migrate_step(model, sid, {rid}, inputs, steps,
                                  deadline, on_chunk, chunks_out, last)

    def _try_step(self, r, model, sid, inputs, steps, deadline,
                  on_chunk, chunks_out, attempts=3):
        """Step on one replica with bounded transient-fault retries.
        Returns ``("ok", result)`` or ``("failed", last_error)`` (the
        caller migrates); raises directly for outcomes that must NOT
        migrate (overload, deadline, anything after chunks went out —
        a re-run elsewhere would resend them)."""
        last = None
        for attempt in range(attempts):
            try:
                # each owner-retry attempt is its own span, typed
                # outcome and all — the session failover contract made
                # visible per attempt
                with trace.span("router.session_hop", replica=r.rid,
                                model=model, sid=sid, attempt=attempt):
                    return "ok", r.session_step(model, sid, inputs,
                                                steps=steps,
                                                deadline_ms=deadline,
                                                on_chunk=on_chunk)
            except (QueueFullError, DeadlineExceeded):
                raise              # overload/deadline: surface as-is
            except ShuttingDown as e:
                if chunks_out[0]:
                    raise          # resend rule: break typed
                return "failed", e     # draining: migrate now
            except ConnectionError as e:
                last = e
                if chunks_out[0]:
                    raise          # resend rule: break typed
                if attempt < attempts - 1:
                    time.sleep(0.01 * (attempt + 1))
        return "failed", last

    def _migrate_step(self, model, sid, exclude, inputs, steps,
                      deadline, on_chunk, chunks_out, last):
        """Owner is dead/draining: re-home the session from its latest
        snapshot onto a surviving replica, then run the step there."""
        candidates = sorted(
            (r for r in self.fleet.routable()
             if r.rid not in exclude),
            key=lambda r: (r.inflight, r.rid))
        if not candidates:
            if self.fleet.all_draining():
                raise FleetDrainingError(
                    "fleet is draining, not accepting session work")
            if last is not None:
                raise last
            raise ReplicaUnavailableError(
                f"no surviving replica to adopt session {sid!r}")
        for r2 in candidates:
            try:
                r2.session_adopt(model, sid)
            except SessionLostError:
                # the typed arm of the contract: no usable snapshot
                # anywhere — drop the affinity so a retry 404s fast
                self.metrics.record_session_loss()
                flightrec.record(flightrec.SESSION, "session.lost",
                                 severity="error", sid=sid,
                                 model=model)
                with self._session_lock:
                    self._session_homes.pop(sid, None)
                raise
            except (ConnectionError, ServingError) as e:
                last = e
                continue
            self.metrics.record_migration()
            trace.add_event("router.session_migrated", sid=sid,
                            to_replica=r2.rid)
            flightrec.record(flightrec.SESSION, "session.migrated",
                             sid=sid, model=model, to_replica=r2.rid)
            with self._session_lock:
                self._session_homes[sid] = (model, r2.rid)
            # the post-adoption step gets the same transient-fault
            # retries as the owner path (an injected replica fault on
            # the hop right after adoption must not leak raw)
            outcome, value = self._try_step(r2, model, sid, inputs,
                                            steps, deadline, on_chunk,
                                            chunks_out)
            if outcome == "ok":
                return value
            raise value
        raise last

    def session_close(self, model, sid):
        rid = self._session_home(model, sid)
        with self._session_lock:
            self._session_homes.pop(sid, None)
        self._ha_publish()   # peers must stop seeing it as ours
        if rid is None:
            # adopted orphan that never stepped here: nothing replica-
            # side to tear down, the affinity drop above is the close
            return {"session_id": sid, "closed": True, "steps": None,
                    "note": "adopted orphan, no local replica owner"}
        try:
            return self.fleet.get(rid).session_close(model, sid)
        except (KeyError, ConnectionError, ShuttingDown) as e:
            # the owner is gone — so is the carry; the close verb's
            # goal (stop tracking, free resources) is already met
            return {"session_id": sid, "closed": True, "steps": None,
                    "note": f"owner {rid} unreachable "
                            f"({type(e).__name__})"}

    # -- fleet health view --------------------------------------------

    def health(self):
        """``(code, body)`` for the router's ``/healthz``: fleet-level
        status + the per-replica state machine."""
        states = self.fleet.states()
        ready = sum(1 for st in states.values()
                    if st["state"] == "ready" and st["healthy"])
        if self.fleet.all_draining():
            status = "draining"
        elif ready == 0:
            status = "unavailable"
        elif ready < len(states):
            # anything short of full strength — including dead
            # replicas that will never return — is an operator signal
            status = "degraded"
        else:
            status = "ok"
        models = set(self.fleet.models)
        if self.autoscaler is not None:
            # managed models belong in the catalog even while scaled
            # to zero — absent would read as "never heard of it"
            models |= set(self.autoscaler.policies())
        body = {
            "status": status,
            "uptime_s": round(time.monotonic() - self.t_start, 3),
            "ready": ready,
            "replicas": states,
            "models": sorted(models),
        }
        if self.autoscaler is not None:
            # additive (docs/serving.md "Autoscaling"): probers that
            # pin the PR 8 shape never see the key without a control
            # plane attached
            body["autoscale"] = self.autoscaler.describe()
        if trace.active():
            # same additive discipline for request-scoped tracing
            body["trace"] = trace.health_block()
        if flightrec.active():
            # and for the always-on flight recorder: present only once
            # events were recorded (a bare router keeps its shape)
            body["flight"] = flightrec.health_block()
        if self.ha is not None:
            # additive (docs/serving.md "Router high availability"):
            # only a router with HA configured grows the block
            body["router_ha"] = self.ha.describe()
        return (200 if ready else 503), body

    def describe(self):
        """Operator view of the routing tier: fleet states, session
        affinity count, and — when a control plane is attached — the
        additive ``"autoscale"`` desired-vs-actual block."""
        states = self.fleet.states()
        out = {
            "replicas": states,
            "ready": sum(1 for st in states.values()
                         if st["state"] == "ready" and st["healthy"]),
            "models": sorted(set(self.fleet.models)
                             | (set(self.autoscaler.policies())
                                if self.autoscaler is not None
                                else set())),
            "sessions": len(self._session_homes),  # mxlint: disable=MX-GUARD001(GIL-atomic len() for an advisory gauge — same contract as the attach_session_count lambda)
            "failovers": self.failovers,
            "hedge": self.hedge,
        }
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.describe()
        if trace.active():
            out["trace"] = trace.health_block()
        if flightrec.active():
            out["flight"] = flightrec.health_block()
        if self.ha is not None:
            out["router_ha"] = self.ha.describe()
        return out

    # -- HTTP front end -----------------------------------------------

    def start(self):
        self._httpd = ServingHTTPServer((self.host, self.port),
                                        _RouterHandler)
        self._httpd.app = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router-http",
            daemon=True)
        self._thread.start()
        if self.ha is not None:
            # advertise a reachable address to peers, then join the
            # membership (first beat is synchronous: a router that
            # cannot lease fails loudly at startup, not silently later)
            adv = ("127.0.0.1" if self.host in ("", "0.0.0.0", "::")
                   else self.host)
            self.ha.addr = f"{adv}:{self.port}"
            self.ha.start()
        return self.port

    def shutdown(self, drain=True, timeout=30.0):
        """Stop routing; with ``drain`` also drain + close the fleet
        (replicas finish in-flight work first)."""
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.ha is not None:
            # leave the membership FIRST: peers see a clean
            # ``router.exited`` departure, not a lease expiry + takeover
            self.ha.stop(leave=True)
        if drain:
            self.fleet.shutdown(timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.metrics.unregister_from_profiler()


class _RouterHandler(JSONRequestHandler):

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            code, body = self.app.health()
            return self._send(code, body)
        if path == "/metrics":
            return self._send(200, self.app.metrics.render().encode(),
                              content_type="text/plain; version=0.0.4")
        if path == "/v1/trace":
            return self._trace_dump("router")
        if path == "/v1/flight":
            return self._flight_dump("router")
        self._send(404, {"error": "NotFound", "message": path})

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/models/") and ":" in path:
            name, _, verb = path[len("/v1/models/"):].rpartition(":")
            handler = {"predict": self._predict,
                       "reload": self._reload,
                       "load": self._load,
                       "unload": self._unload}.get(verb)
            if handler is not None and name:
                return handler(name)
        parsed = self.parse_session_path(path)
        if parsed is not None:
            model, sid, verb = parsed
            if verb == "create" and sid is None:
                return self._session_create(model)
            if sid is not None:
                if (self.app.ha is not None
                        and self._forward_session(path, sid)):
                    return   # proxied to the owning peer router
                handler = {"step": self._session_step,
                           "close": self._session_close}.get(verb)
                if handler is not None:
                    return handler(model, sid)
        self._send(404, {"error": "NotFound", "message": path})

    def _forward_session(self, path, sid):
        """HA session affinity: if ``sid`` is owned by a live PEER
        router, proxy the request there (one ``X-MXNET-ROUTER`` hop)
        and relay the answer.  Returns True when the request was
        handled here (forwarded, or answered with a typed loop/hop
        error), False when the local router should serve it.

        Garbled or stale headers are *ignored*, never 500'd — the
        header is advisory loop-accounting, not an auth token."""
        ha = self.app.ha
        hops, via = routerha.parse_forward_header(
            self.headers.get(routerha.HEADER))
        target = ha.forward_target(sid)
        if target is None:
            return False          # ours (or claimable): serve locally
        rid, addr = target
        if hops >= ha.forward_hops or ha.router_id in via:
            # loop detected / budget exhausted — typed, bounded, 508
            self._send(508, {
                "error": "RouterForwardError",
                "message": (
                    f"session {sid!r}: forward-hop budget "
                    f"({ha.forward_hops}) exhausted at router "
                    f"{ha.router_id!r} (via {list(via)}); membership "
                    f"views disagree about ring ownership")})
            return True

        def fn():
            import urllib.error
            import urllib.request
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            fault.inject("serving.router_forward",
                         f"{sid}->{rid}")
            req = urllib.request.Request(
                f"http://{addr}{path}", data=raw,
                headers={"Content-Type": "application/json",
                         routerha.HEADER:
                             routerha.forward_header_value(
                                 hops + 1, via + (ha.router_id,))})
            tid = self.headers.get(trace.HEADER)
            if tid:
                req.add_header(trace.HEADER, tid)
            ha.note_forward()
            trace.add_event("router.forwarded", sid=sid, to_router=rid)
            flightrec.record(flightrec.MEMBERSHIP, "router.forwarded",
                             sid=sid, to_router=rid)
            try:
                resp = urllib.request.urlopen(req, timeout=120)
            except urllib.error.HTTPError as e:
                # relay the peer's typed answer verbatim (410/503/...)
                body = e.read()
                self._send(e.code, body or b"{}",
                           content_type="application/json")
                return
            except (urllib.error.URLError, OSError) as e:
                raise RouterLeaseError(
                    f"forward of session {sid!r} to router {rid!r} "
                    f"({addr}) failed: {e}") from None
            with resp:
                if (resp.headers.get("Transfer-Encoding", "")
                        .lower() == "chunked"):
                    # relay the peer's decode stream line by line
                    self._start_chunked(resp.status)
                    for line in resp:
                        line = line.strip()
                        if line:
                            self._write_chunk(json.loads(line))
                    self._end_chunked()
                else:
                    self._send(resp.status, resp.read() or b"{}",
                               content_type="application/json")
        self._guarded(fn)
        return True

    def _guarded(self, fn):
        """Map the typed routing errors onto HTTP, with a live-derived
        Retry-After on every retryable condition."""
        try:
            return fn()
        except ClientDisconnected:
            pass       # socket is gone; counted where it was detected
        except (SessionExpiredError, SessionLostError) as e:
            # typed + terminal for that session id: 410 Gone
            self._send(410, {"error": type(e).__name__,
                             "message": str(e)})
        except ServingError as e:
            hdrs = (self.app._retry_headers()
                    if e.http_status in (429, 503) else None)
            self._send(e.http_status, e.payload(), extra_headers=hdrs)
        except FleetDrainingError as e:
            self._send(503, {"error": "FleetDrainingError",
                             "message": str(e)},
                       extra_headers=self.app._retry_headers())
        except RouterForwardError as e:
            # forward-hop budget exhausted: a routing loop, not a
            # transient — 508 Loop Detected, retry after the
            # membership view converges
            self._send(508, {"error": "RouterForwardError",
                             "message": str(e)})
        except fault.TransientFault as e:
            self._send(503, {"error": "TransientFault",
                             "message": str(e)},
                       extra_headers=self.app._retry_headers())
        except ConnectionError as e:
            # ReplicaUnavailableError and raw refused sockets: the
            # condition clears when a replica re-warms
            self._send(503, {"error": type(e).__name__,
                             "message": str(e)},
                       extra_headers=self.app._retry_headers())
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            self._send(500, {"error": type(e).__name__,
                             "message": str(e)})

    def _predict(self, name):
        # the trace is born at the fleet's front door (or adopted from
        # the client's own header): every hop, hedge and failover below
        # parents onto this span, and the header echo hands the id
        # back to the client for /v1/trace
        tspan = trace.from_header(self.headers.get(trace.HEADER),
                                  "router.request", model=name)

        def fn():
            with trace.activate(tspan):
                # parse/validate is its own span: the no-dark-latency
                # budget (queue + batch + execute + hops accounted)
                # includes the front end's own body handling
                with trace.span("router.parse", model=name):
                    specs = self.app.model_meta(name)
                    body = self._body()
                    if "inputs" not in body or not isinstance(
                            body["inputs"], list):
                        raise BadRequest(
                            'body needs "inputs": [tensor, ...]')
                    if len(body["inputs"]) != len(specs):
                        raise BadRequest(
                            f"model {name!r} takes {len(specs)} "
                            f"inputs, got {len(body['inputs'])}")
                    try:
                        arrs = tuple(
                            onp.asarray(x, dtype=spec["dtype"])
                            for x, spec in zip(body["inputs"], specs))
                    except (TypeError, ValueError) as e:
                        raise BadRequest(
                            f"malformed input tensor: {e}")
                    for a, spec in zip(arrs, specs):
                        want = tuple(spec["shape"][1:])
                        if tuple(a.shape) != want:
                            raise BadRequest(
                                f"instance shape {tuple(a.shape)} != "
                                f"exported instance shape {want}")
                outputs, timing = self.app.route(
                    name, arrs, deadline_ms=body.get("timeout_ms"),
                    inputs_json=json.dumps(body["inputs"]),
                    live=lambda: not self._client_gone())
            if tspan is not None:
                tspan.set(code=200)
                tspan.finish()
            self._send(200, {
                "outputs": [o if isinstance(o, list)
                            else onp.asarray(o).tolist()
                            for o in outputs],
                "timing": {k: round(v, 3)
                           for k, v in (timing or {}).items()
                           if v is not None}},
                extra_headers={trace.HEADER: trace.header_value(tspan)}
                if tspan is not None else None)

        try:
            self._guarded(fn)
        finally:
            # error paths were answered by _guarded; the span closes
            # with a generic error outcome (the failing hop span below
            # it carries the typed one)
            if tspan is not None and not tspan.done:
                tspan.finish(outcome="error")

    def _reload(self, name):
        def fn():
            body = self._body()
            report = self.app.fleet.rolling_reload(
                name, path=body.get("path"),
                version=body.get("version"))
            self._send(200, report)
        self._guarded(fn)

    def _load(self, name):
        def fn():
            body = self._body()
            if "path" not in body:
                raise BadRequest('load needs {"path": artifact-prefix}')
            self._send(200, self.app.fleet.load_everywhere(
                name, body["path"], version=body.get("version"),
                warmup=body.get("warmup")))
        self._guarded(fn)

    def _unload(self, name):
        def fn():
            self._send(200, self.app.fleet.unload_everywhere(name))
        self._guarded(fn)

    # -- sessions -----------------------------------------------------

    def _session_create(self, model):
        def fn():
            body = self._body()
            self._send(200, self.app.session_create(
                model, body.get("session_id")))
        self._guarded(fn)

    def _session_close(self, model, sid):
        def fn():
            self._send(200, self.app.session_close(model, sid))
        self._guarded(fn)

    def _session_step(self, model, sid):
        def fn():
            body = self._body()
            if "inputs" not in body or not isinstance(body["inputs"],
                                                      list):
                raise BadRequest('body needs "inputs": [tensor, ...]')
            steps = body.get("steps", 1)
            deadline = body.get("timeout_ms")
            if body.get("stream"):
                return self._session_stream(model, sid,
                                            body["inputs"], steps,
                                            deadline)
            chunks, timing = self.app.session_step(
                model, sid, tuple(body["inputs"]), steps=steps,
                deadline_ms=deadline)
            self._send(200, {
                "session_id": sid,
                "steps": timing.get("steps", len(chunks)),
                "outputs": [[onp.asarray(leaf).tolist()
                             for leaf in chunk] for chunk in chunks],
                "timing": {k: round(v, 3)
                           for k, v in (timing or {}).items()
                           if isinstance(v, (int, float))}})
        self._guarded(fn)

    def _session_stream(self, model, sid, inputs, steps, deadline):
        """Relay a replica's chunked decode stream to the client,
        chunk by chunk.  A broken client pipe cancels the relay (the
        replica sees its socket close and cancels the stream); a
        replica death mid-relay surfaces as an in-band typed error
        line — the stream breaks VISIBLY, and the session itself
        recovers via migration on the next step."""
        started = [False]

        def relay(chunk):
            if not started[0]:
                self._start_chunked(200)
                started[0] = True
            try:
                self._write_chunk({
                    "session_id": sid,
                    "outputs": [onp.asarray(leaf).tolist()
                                for leaf in chunk]})
            except OSError as e:
                self.app.metrics.record_route_cancel()
                raise ClientDisconnected(
                    f"stream client of {model!r}/{sid} vanished: "
                    f"{type(e).__name__}") from e

        try:
            chunks, timing = self.app.session_step(
                model, sid, tuple(inputs), steps=steps,
                deadline_ms=deadline, on_chunk=relay)
        except ClientDisconnected:
            raise
        except (ServingError, SessionExpiredError, SessionLostError,
                FleetDrainingError, ConnectionError) as e:
            if not started[0]:
                raise    # nothing sent yet: normal error mapping
            self._write_chunk({"error": type(e).__name__,
                               "message": str(e)})
            self._end_chunked()
            return
        if not started[0]:
            self._start_chunked(200)
        self._write_chunk({
            "done": True, "session_id": sid,
            "steps": timing.get("steps", len(chunks)),
            "timing": {k: round(v, 3)
                       for k, v in (timing or {}).items()
                       if isinstance(v, (int, float))}})
        self._end_chunked()


def main(argv=None):
    import argparse
    import signal

    from .fleet import ReplicaFleet

    p = argparse.ArgumentParser(
        description="mxnet-tpu multi-replica serving fleet router")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PREFIX",
                   help="serve artifact PREFIX as model NAME on every "
                        "replica")
    p.add_argument("--session-model", action="append", default=[],
                   metavar="NAME=SPEC",
                   help="host a stateful session model on every "
                        "replica (sessions.SESSION_MODELS spec)")
    p.add_argument("--session-dir", default=None,
                   help="shared snapshot dir for session migration "
                        "(default MXNET_SERVING_SESSION_DIR)")
    p.add_argument("--managed-model", action="append", default=[],
                   metavar="NAME=PREFIX[,slo=CLASS][,min=N][,max=N]",
                   help="hand model NAME to the autoscaling control "
                        "plane instead of pre-loading it everywhere: "
                        "scale-to-zero when idle, on-demand AOT "
                        "reload, HBM bin-packing (docs/serving.md "
                        "\"Autoscaling\")")
    p.add_argument("--hbm-budget", type=int, default=None,
                   help="per-replica packing budget in bytes "
                        "(default MXNET_SERVING_REPLICA_HBM_BUDGET; "
                        "0 = unlimited)")
    p.add_argument("--replicas", type=int,
                   default=get_env("MXNET_SERVING_FLEET_REPLICAS", 2,
                                   int))
    p.add_argument("--backend", choices=("thread", "process"),
                   default="process",
                   help="replica isolation (process = one server "
                        "subprocess per replica)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int,
                   default=get_env("MXNET_SERVING_PORT", 8080, int))
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--ha-dir", default=None,
                   help="shared lease directory enabling the HA "
                        "router tier (default "
                        "MXNET_SERVING_ROUTER_HA_DIR; unset = HA off)")
    p.add_argument("--router-id", default=None,
                   help="stable member id in the HA lease store "
                        "(default MXNET_SERVING_ROUTER_ID or "
                        "router-<pid>)")
    p.add_argument("--lease-ttl", type=float, default=None,
                   help="HA lease TTL seconds (default "
                        "MXNET_SERVING_ROUTER_LEASE_TTL_S)")
    args = p.parse_args(argv)

    models = {}
    for spec in args.model:
        name, sep, path = spec.partition("=")
        if not sep:
            p.error(f"--model wants NAME=PREFIX, got {spec!r}")
        models[name] = path
    session_models = {}
    for spec in args.session_model:
        name, sep, model_spec = spec.partition("=")
        if not sep:
            p.error(f"--session-model wants NAME=SPEC, got {spec!r}")
        session_models[name] = model_spec
    policies = []
    for spec in args.managed_model:
        name, sep, rest = spec.partition("=")
        if not sep:
            p.error(f"--managed-model wants NAME=PREFIX[,k=v...], "
                    f"got {spec!r}")
        path, *opts = rest.split(",")
        kw = {}
        for opt in opts:
            k, sep2, v = opt.partition("=")
            if not sep2 or k not in ("slo", "min", "max"):
                p.error(f"--managed-model option {opt!r}: want "
                        f"slo=CLASS, min=N or max=N")
            if k == "slo":
                kw["slo"] = v
            else:
                kw["min_replicas" if k == "min"
                   else "max_replicas"] = int(v)
        from .autoscaler import ModelPolicy
        policies.append(ModelPolicy(name, path, **kw))
    if not models and not session_models and not policies:
        p.error("need at least one --model, --session-model or "
                "--managed-model")

    # black box: name this process in flight dumps and arm the SIGUSR2
    # wedge-dump path (docs/observability.md "Flight recorder")
    flightrec.install_signal_handler(proc="router")
    flightrec.record(flightrec.LIFECYCLE, "router.started",
                     replicas=args.replicas, backend=args.backend)

    fleet = ReplicaFleet(models, n=args.replicas, backend=args.backend,
                         warmup=not args.no_warmup,
                         session_models=session_models,
                         session_dir=args.session_dir)
    print(f"[fleet] spawning {args.replicas} {args.backend} "
          f"replica(s)", flush=True)
    fleet.spawn()
    router = FleetRouter(fleet, host=args.host, port=args.port,
                         router_id=args.router_id, ha_dir=args.ha_dir,
                         lease_ttl_s=args.lease_ttl)
    if router.ha is not None:
        print(f"[fleet] router HA member {router.ha.router_id!r} "
              f"(lease ttl {router.ha.lease_ttl_s:g}s, store "
              f"{args.ha_dir or 'env'})", flush=True)
    if policies:
        from .autoscaler import Autoscaler
        from .placement import Placer
        scaler = Autoscaler(
            fleet, router=router, policies=policies,
            placer=Placer(budget_bytes=args.hbm_budget))
        scaler.start()
        print(f"[fleet] autoscaling {len(policies)} managed model(s) "
              f"every {scaler.interval_s:g}s "
              f"(idle-unload {scaler.idle_unload_s:g}s, "
              f"<= {scaler.max_replicas} replicas)", flush=True)
    port = router.start()
    print(f"[fleet] routing on {args.host}:{port} over "
          f"{fleet.ready_count()} ready replica(s)", flush=True)

    done = threading.Event()

    def stop(signum, frame):
        print(f"[fleet] signal {signum}: draining fleet", flush=True)
        done.set()

    signal.signal(signal.SIGTERM, stop)
    signal.signal(signal.SIGINT, stop)
    done.wait()
    router.shutdown(drain=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
