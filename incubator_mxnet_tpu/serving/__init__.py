"""Serving subsystem: dynamic-batching inference over deploy artifacts.

The training side of this framework got dispatch-lean (op bulking) and
fault-tolerant (kvstore/checkpoint hardening); this package is the
request path the ROADMAP's "heavy traffic" north star needs — the
TPU-era analog of the reference's predict-only runtime
(c_predict_api.cc) grown into a server, in the shape of Clipper's
adaptive batching layer (NSDI'17) and MXNet Model Server:

* :mod:`.model_repository` — versioned registry over
  ``deploy.load_predictor`` artifacts with warmup (one pre-compiled
  executable per padding bucket) and atomic reload.
* :mod:`.batcher` — per-model dynamic batcher: concurrent single
  requests coalesce into padded bucket-sized batches (on TPU every
  distinct shape is a fresh XLA compile, so padding buckets are load-
  bearing, not a nicety), flushed on size or latency.
* :mod:`.admission` — bounded queues (429), deadlines (504 with the
  queue-vs-compute split), graceful drain, fault-injection hooks.
* :mod:`.server` — stdlib ``ThreadingHTTPServer`` front end:
  ``POST /v1/models/{name}:predict``, ``/healthz``, ``/metrics`` and
  admin load/unload/reload.
* :mod:`.metrics` — Prometheus-text counters/histograms, also folded
  into ``profiler.dumps()`` alongside ``bulk_stats``.
* :mod:`.fleet` + :mod:`.router` — the multi-replica tier: N replicas
  (in-process or subprocess) behind a health-checked router with
  least-loaded placement, per-hop deadline budgets, bounded failover,
  hedged requests and zero-downtime rolling reload.
* :mod:`.sessions` — stateful sessions with continuous batching:
  per-session carry trees (KV-cache-style), create/step/close verbs,
  chunked streaming, TTL + bounded-count eviction, periodic CRC'd
  snapshots (checkpoint.py shard format) and the crash-safe failover
  contract: migrate-from-snapshot (bitwise continuation) or typed
  ``SessionLostError`` — never a hang, never a silent restart.
* :mod:`.routerha` — the highly-available router tier: N routers
  share one view of the fleet and of session ownership through leased
  membership (join/heartbeat/expire over a pluggable shared store),
  consistent-hash session affinity with bounded ``X-MXNET-ROUTER``
  forward hops, and crash takeover — an expired router's sessions
  rehash to the survivors and resume via the same snapshot-restore
  path a replica death uses.  Fully off (zero threads, zero lease
  traffic, pinned bare shapes) unless explicitly configured.
* :mod:`.autoscaler` + :mod:`.placement` — the multi-tenant control
  plane: a level-triggered loop over the router's own metrics that
  grows/shrinks the fleet per model (scale-from-zero via the AOT
  artifact path, idle unload), packs models onto replicas under
  memlint's peak-HBM budget with LRU eviction, and serves each model
  under an SLO class (priority admission, weighted fair queueing,
  shed-low-first at 429).

Everything is pure stdlib + JAX; no new dependencies.
"""
from .admission import (DeadlineExceeded, QueueFullError,   # noqa: F401
                        ServingError, ShuttingDown, SloClass,
                        slo_class)
from .autoscaler import Autoscaler, ModelPolicy              # noqa: F401
from .batcher import (ContinuousBatcher, DynamicBatcher,     # noqa: F401
                      WeightedFairGate)
from .fleet import ReplicaFleet                              # noqa: F401
from .metrics import FleetMetrics, ServingMetrics            # noqa: F401
from .model_repository import ModelRepository                # noqa: F401
from .placement import Placer                                # noqa: F401
from .router import FleetRouter                              # noqa: F401
from .routerha import RouterHA                               # noqa: F401
from .server import InferenceServer                          # noqa: F401
from .sessions import (SessionHost, SessionManager,          # noqa: F401
                       SessionModel)

__all__ = ["ModelRepository", "DynamicBatcher", "ContinuousBatcher",
           "InferenceServer", "ReplicaFleet", "FleetRouter",
           "SessionManager", "SessionModel", "SessionHost",
           "ServingMetrics", "FleetMetrics", "ServingError",
           "QueueFullError", "DeadlineExceeded", "ShuttingDown",
           "Autoscaler", "ModelPolicy", "Placer", "SloClass",
           "slo_class", "WeightedFairGate", "RouterHA"]
