"""HTTP front end: stdlib ``ThreadingHTTPServer`` over the repository.

Endpoints (KFServing-style verbs, stdlib-only implementation):

* ``POST /v1/models/{name}:predict``  — ``{"inputs": [tensor, ...],
  "timeout_ms": n?}`` where each tensor is a nested JSON list shaped
  like the exported input minus its leading batch dim.  Responds
  ``{"outputs": [...], "timing": {"queue_ms":, "compute_ms":}}``.
* ``GET  /healthz``   — liveness + per-model vitals (the serving twin
  of PR 2's kvstore ``heartbeat`` probe: cheap, never touches the
  device, and reports queue depths so a scheduler can drain early);
  503 while draining.
* ``GET  /metrics``   — Prometheus text exposition.
* ``POST /v1/models/{name}:load``    — ``{"path":, "version"?:,
  "warmup"?:}`` admin verbs; ``:unload``; ``:reload`` (atomic swap,
  in-flight requests finish on the old version).

Each handler thread blocks inside ``DynamicBatcher.submit`` while its
request rides a coalesced batch — ThreadingHTTPServer gives us the
per-request threads, the batcher turns them into bucket-sized device
launches.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as onp

from ..base import get_env
from .. import fault
from .admission import Admission, BadRequest, ServingError
from .metrics import ServingMetrics
from .model_repository import ModelRepository

__all__ = ["InferenceServer", "health_body", "main"]


def health_body(repository, t_start=None):
    """Build the structured ``/healthz`` response: ``(code, body)``.

    Per-model ``state`` is the probe contract the fleet layer routes
    on (docs/serving.md):

    * ``loading``  — a build (initial load, or a reload's replacement)
      is warming; the name is not serving yet (or still serving the
      old version).  A prober must NOT admit a replica on this.
    * ``ready``    — loaded, warmed, taking traffic.
    * ``draining`` — admission stopped; in-flight work finishing.

    Queue depth rides along per model (and summed at the top level) so
    schedulers can shed load before the 429 bound bites.  Shared by
    the HTTP handler and the in-process fleet replicas, so the two
    probe paths can never disagree on shape."""
    draining = repository.admission.draining
    models = {}
    total_depth = 0
    for name, d in repository.models().items():
        total_depth += d["queue_depth"]
        models[name] = {
            "state": "draining" if draining else "ready",
            "version": d["version"],
            "queue_depth": d["queue_depth"],
            "compile_count": d["compile_count"],
            # how expensive this replica's readiness was, and whether
            # the AOT artifact layer carried it (compile_count 0 with
            # aot_buckets = cold start was deserialization) — the
            # numbers an autoscaler sizes spawn lead time from
            "cold_start_ms": d["cold_start_ms"],
            "aot_buckets": d["aot_buckets"],
        }
    for name in repository.loading_names():
        if name not in models:
            models[name] = {"state": "loading", "version": None,
                            "queue_depth": 0, "compile_count": None,
                            "cold_start_ms": None, "aot_buckets": []}
    body = {
        "status": "draining" if draining else "ok",
        "uptime_s": (round(time.monotonic() - t_start, 3)
                     if t_start is not None else None),
        "queue_depth": total_depth,
        "models": models,
    }
    return (503 if draining else 200), body


class ServingHTTPServer(ThreadingHTTPServer):
    """Shared listener for the single-server and fleet front ends."""
    daemon_threads = True
    allow_reuse_address = True
    # stdlib default backlog is 5: a burst of >5 concurrent connects
    # overflows the SYN queue and the extras stall a full ~1s TCP
    # retransmit — measured as a 1023ms p99 on an 8-client volley
    request_queue_size = 128


class JSONRequestHandler(BaseHTTPRequestHandler):
    """Shared handler plumbing (JSON send/parse, quiet logging) for
    the single-server and fleet-router front ends — one place to fix
    Content-Length/encoding/backpressure behaviour for both."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if get_env("MXNET_SERVING_VERBOSE", False, bool):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    @property
    def app(self):
        return self.server.app

    def _send(self, code, body, content_type="application/json",
              extra_headers=None):
        data = (body if isinstance(body, bytes)
                else json.dumps(body).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        try:
            return json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, UnicodeDecodeError) as e:
            raise BadRequest(f"request body is not JSON: {e}")


class _Handler(JSONRequestHandler):

    # -- routes -------------------------------------------------------

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            return self._healthz()
        if path == "/metrics":
            return self._send(200, self.app.metrics.render().encode(),
                              content_type="text/plain; version=0.0.4")
        if path == "/v1/models":
            return self._send(200, {"models": self.app.repository.models()})
        self._send(404, {"error": "NotFound", "message": path})

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/models/") and ":" in path:
            name, _, verb = path[len("/v1/models/"):].rpartition(":")
            handler = {"predict": self._predict, "load": self._load,
                       "unload": self._unload,
                       "reload": self._reload}.get(verb)
            if handler is not None and name:
                return handler(name)
        self._send(404, {"error": "NotFound", "message": path})

    # -- handlers -----------------------------------------------------

    def _healthz(self):
        code, body = health_body(self.app.repository, self.app.t_start)
        self._send(code, body)

    def _predict(self, name):
        t0 = time.monotonic()
        code, timing, payload, hdrs = 500, {}, None, None
        try:
            # resolve the model FIRST: every later error (400/5xx) is
            # then attributed to a registry-backed name, so arbitrary
            # client-supplied names cannot grow the metrics registry
            entry = self.app.repository.get(name)
            body = self._body()
            if "inputs" not in body or not isinstance(body["inputs"],
                                                      list):
                raise BadRequest('body needs "inputs": [tensor, ...]')
            specs = entry.predictor.meta["inputs"]
            if len(body["inputs"]) != len(specs):
                raise BadRequest(
                    f"model {name!r} takes {len(specs)} inputs, got "
                    f"{len(body['inputs'])}")
            try:
                arrs = tuple(
                    onp.asarray(x, dtype=spec["dtype"])
                    for x, spec in zip(body["inputs"], specs))
            except (TypeError, ValueError) as e:
                raise BadRequest(f"malformed input tensor: {e}")
            for a, spec in zip(arrs, specs):
                want = tuple(spec["shape"][1:])
                if tuple(a.shape) != want:
                    raise BadRequest(
                        f"instance shape {tuple(a.shape)} != exported "
                        f"instance shape {want}")
            out, timing = self.app.repository.predict(
                name, arrs, body.get("timeout_ms"))
            import jax
            outputs = [o.tolist()
                       for o in jax.tree_util.tree_leaves(out)]
            code = 200
            payload = {"outputs": outputs,
                       "timing": {k: round(v, 3)
                                  for k, v in timing.items()
                                  if v is not None}}
        except ServingError as e:
            code = e.http_status
            hdrs = {"Retry-After": "1"} if code in (429, 503) else None
            payload = e.payload()
        except fault.TransientFault as e:
            code = 503   # injected front-end fault: client may retry
            payload = {"error": "TransientFault", "message": str(e)}
            hdrs = {"Retry-After": "1"}
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            code = 500
            payload = {"error": type(e).__name__, "message": str(e)}
        # record BEFORE sending: the moment the response bytes go out,
        # the client may scrape /metrics, and its own request must
        # already be counted.  Unknown-model 404s are not attributed
        # per-model: arbitrary client-supplied names must not grow the
        # metrics registry.
        if code != 404:
            e2e = (time.monotonic() - t0) * 1000.0
            self.app.metrics.record_request(
                name, code, e2e_ms=e2e,
                compute_ms=timing.get("compute_ms"),
                queue_ms=timing.get("queue_ms"))
        self._send(code, payload, extra_headers=hdrs)

    def _admin(self, name, fn):
        # errors attribute to the name only when it names a loaded
        # model (a failed :load of an arbitrary name must not mint a
        # metrics entry); successes always do — :load just created it
        try:
            result = fn(self._body())
            self.app.metrics.record_request(name, 200)
            self._send(200, result)
        except ServingError as e:
            if e.http_status != 404 and self.app.repository.has(name):
                self.app.metrics.record_request(name, e.http_status)
            self._send(e.http_status, e.payload())
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            if self.app.repository.has(name):
                self.app.metrics.record_request(name, 500)
            self._send(500, {"error": type(e).__name__,
                             "message": str(e)})

    def _load(self, name):
        def fn(body):
            if "path" not in body:
                raise BadRequest('load needs {"path": artifact-prefix}')
            return self.app.repository.load(
                name, body["path"], version=body.get("version"),
                warmup=body.get("warmup"))
        self._admin(name, fn)

    def _unload(self, name):
        self._admin(name, lambda body:
                    self.app.repository.unload(name))

    def _reload(self, name):
        def fn(body):
            return self.app.repository.reload(
                name, path=body.get("path"),
                version=body.get("version"),
                warmup=body.get("warmup"))
        self._admin(name, fn)


class InferenceServer:
    """Own the repository + metrics + HTTP listener as one unit."""

    def __init__(self, repository=None, host="127.0.0.1", port=0,
                 metrics=None):
        # adopt the repository's metrics when it already has one, so
        # handler-side counters and batcher-side counters land in the
        # same instance; otherwise rebind the repository (and its live
        # batchers) to ours
        if metrics is None and repository is not None:
            metrics = repository.metrics
        self.metrics = metrics or ServingMetrics()
        self.repository = repository or ModelRepository(
            metrics=self.metrics)
        if self.repository.metrics is not self.metrics:
            self.repository.set_metrics(self.metrics)
        else:
            self.metrics.attach_repository(self.repository)
        self.metrics.register_with_profiler()
        self.host = host
        self.port = int(port)
        self.t_start = time.monotonic()
        self._httpd = None
        self._thread = None

    def start(self):
        """Bind + serve on a background thread; returns the bound port
        (ephemeral when constructed with port=0)."""
        self._httpd = ServingHTTPServer((self.host, self.port),
                                        _Handler)
        self._httpd.app = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self, drain=True, timeout=30.0):
        """Graceful stop: drain queues first so queued requests get
        real responses, then close the listener."""
        if drain:
            self.repository.drain_all(timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.metrics.unregister_from_profiler()


def main(argv=None):
    import argparse
    import signal

    p = argparse.ArgumentParser(
        description="mxnet-tpu dynamic-batching inference server")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PREFIX",
                   help="load artifact PREFIX as model NAME at startup")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int,
                   default=get_env("MXNET_SERVING_PORT", 8080, int))
    p.add_argument("--no-warmup", action="store_true",
                   help="skip per-bucket warmup compiles at load")
    args = p.parse_args(argv)

    server = InferenceServer(host=args.host, port=args.port)
    for spec in args.model:
        name, sep, path = spec.partition("=")
        if not sep:
            p.error(f"--model wants NAME=PREFIX, got {spec!r}")
        server.repository.load(name, path,
                               warmup=not args.no_warmup)
        print(f"[serving] loaded {name} from {path}", flush=True)
    port = server.start()
    print(f"[serving] listening on {args.host}:{port}", flush=True)

    done = threading.Event()

    def stop(signum, frame):
        print(f"[serving] signal {signum}: draining", flush=True)
        done.set()

    signal.signal(signal.SIGTERM, stop)
    signal.signal(signal.SIGINT, stop)
    done.wait()
    server.shutdown(drain=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
