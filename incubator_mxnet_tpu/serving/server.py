"""HTTP front end: stdlib ``ThreadingHTTPServer`` over the repository.

Endpoints (KFServing-style verbs, stdlib-only implementation):

* ``POST /v1/models/{name}:predict``  — ``{"inputs": [tensor, ...],
  "timeout_ms": n?}`` where each tensor is a nested JSON list shaped
  like the exported input minus its leading batch dim.  Responds
  ``{"outputs": [...], "timing": {"queue_ms":, "compute_ms":}}``.
* ``GET  /healthz``   — liveness + per-model vitals (the serving twin
  of PR 2's kvstore ``heartbeat`` probe: cheap, never touches the
  device, and reports queue depths so a scheduler can drain early);
  503 while draining.
* ``GET  /metrics``   — Prometheus text exposition.
* ``POST /v1/models/{name}:load``    — ``{"path":, "version"?:,
  "warmup"?:}`` admin verbs; ``:unload``; ``:reload`` (atomic swap,
  in-flight requests finish on the old version).

Each handler thread blocks inside ``DynamicBatcher.submit`` while its
request rides a coalesced batch — ThreadingHTTPServer gives us the
per-request threads, the batcher turns them into bucket-sized device
launches.
"""
from __future__ import annotations

import json
import queue as _queue
import select
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as onp

from ..base import get_env
from .. import fault, flightrec, trace
from ..error import SessionExpiredError, SessionLostError
from .admission import (Admission, BadRequest, ClientDisconnected,
                        ServingError, retry_after_s)
from .metrics import ServingMetrics
from .model_repository import ModelRepository

__all__ = ["InferenceServer", "health_body", "main"]


def health_body(repository, t_start=None, sessions=None):
    """Build the structured ``/healthz`` response: ``(code, body)``.

    Per-model ``state`` is the probe contract the fleet layer routes
    on (docs/serving.md):

    * ``loading``  — a build (initial load, or a reload's replacement)
      is warming; the name is not serving yet (or still serving the
      old version).  A prober must NOT admit a replica on this.
    * ``ready``    — loaded, warmed, taking traffic.
    * ``draining`` — admission stopped; in-flight work finishing.

    Queue depth rides along per model (and summed at the top level) so
    schedulers can shed load before the 429 bound bites.  Shared by
    the HTTP handler and the in-process fleet replicas, so the two
    probe paths can never disagree on shape."""
    draining = repository.admission.draining
    models = {}
    total_depth = 0
    for name, d in repository.models().items():
        total_depth += d["queue_depth"]
        models[name] = {
            "state": "draining" if draining else "ready",
            "version": d["version"],
            "queue_depth": d["queue_depth"],
            "compile_count": d["compile_count"],
            # how expensive this replica's readiness was, and whether
            # the AOT artifact layer carried it (compile_count 0 with
            # aot_buckets = cold start was deserialization) — the
            # numbers an autoscaler sizes spawn lead time from
            "cold_start_ms": d["cold_start_ms"],
            "aot_buckets": d["aot_buckets"],
        }
    for name in repository.loading_names():
        if name not in models:
            models[name] = {"state": "loading", "version": None,
                            "queue_depth": 0, "compile_count": None,
                            "cold_start_ms": None, "aot_buckets": []}
    body = {
        "status": "draining" if draining else "ok",
        "uptime_s": (round(time.monotonic() - t_start, 3)
                     if t_start is not None else None),
        "queue_depth": total_depth,
        "models": models,
    }
    # stateful sessions ride along (additively — probers that pin the
    # per-model predict shape never see the key unless session models
    # are actually registered): per session model the pinned describe
    # dict, docs/serving.md "Sessions"
    if sessions is not None and sessions.names():
        body["sessions"] = sessions.describe()
        body["queue_depth"] += sum(
            d["queue_depth"] for d in body["sessions"].values())
    # request-scoped tracing rides along additively too: the key only
    # appears while tracing is observably on (sampling enabled or
    # spans recorded), so bare deployments keep their pinned shape
    if trace.active():
        body["trace"] = trace.health_block()
    # same additive discipline for the always-on flight recorder:
    # present only once events were actually recorded
    if flightrec.active():
        body["flight"] = flightrec.health_block()
    return (503 if draining else 200), body


class ServingHTTPServer(ThreadingHTTPServer):
    """Shared listener for the single-server and fleet front ends."""
    daemon_threads = True
    allow_reuse_address = True
    # stdlib default backlog is 5: a burst of >5 concurrent connects
    # overflows the SYN queue and the extras stall a full ~1s TCP
    # retransmit — measured as a 1023ms p99 on an 8-client volley
    request_queue_size = 128


class JSONRequestHandler(BaseHTTPRequestHandler):
    """Shared handler plumbing (JSON send/parse, quiet logging) for
    the single-server and fleet-router front ends — one place to fix
    Content-Length/encoding/backpressure behaviour for both."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if get_env("MXNET_SERVING_VERBOSE", False, bool):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    @property
    def app(self):
        return self.server.app

    def _send(self, code, body, content_type="application/json",
              extra_headers=None):
        data = (body if isinstance(body, bytes)
                else json.dumps(body).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        try:
            return json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, UnicodeDecodeError) as e:
            raise BadRequest(f"request body is not JSON: {e}")

    def _query(self):
        """Query-string params of the current request (stdlib-only,
        no cgi): ``?a=b&c=d`` → ``{"a": "b", "c": "d"}``."""
        qs = self.path.partition("?")[2]
        out = {}
        for pair in qs.split("&"):
            k, sep, v = pair.partition("=")
            if sep and k:
                out[k] = v
        return out

    def _trace_dump(self, service):
        """``GET /v1/trace[?trace_id=...]`` — this process's span ring
        as Chrome trace-event JSON (tools/traceview.py merges several
        of these into one cross-process timeline)."""
        tid = self._query().get("trace_id") or None
        self._send(200, trace.export(tid, service=service))

    def _flight_dump(self, service):
        """``GET /v1/flight`` — this process's flight-recorder ring as
        a dump (tools/postmortem.py merges several of these, plus any
        crash/SIGUSR2 dump files, into one incident timeline)."""
        self._send(200, flightrec.export(service=service,
                                         reason="http"))

    @staticmethod
    def parse_session_path(path):
        """``/v1/sessions/{model}:create`` or
        ``/v1/sessions/{model}/{sid}:{verb}`` →
        ``(model, sid_or_None, verb)``; ``None`` for anything else.
        One parser for both front ends — the server and the fleet
        router must never grow different session URL surfaces."""
        if not (path.startswith("/v1/sessions/") and ":" in path):
            return None
        target, _, verb = path[len("/v1/sessions/"):].rpartition(":")
        model, _, sid = target.partition("/")
        if not model or not verb:
            return None
        return model, (sid or None), verb

    # -- client-liveness + chunked streaming --------------------------

    def _client_gone(self):
        """True when the client hung up (EOF/reset on its socket).

        Non-consuming: the byte is MSG_PEEKed, so a keep-alive
        client's *next* pipelined request is left intact.  Used while
        a request is queued — a dead client's request is cancelled so
        it stops consuming device time (``PendingResult.cancel``).

        Known tradeoff (nginx's 499 makes the same call): a client
        that half-closes (``shutdown(SHUT_WR)``) after sending its
        request also reads as EOF here and gets cancelled, even
        though its read side could still take the response.
        Half-closing HTTP clients are vanishingly rare; dead clients
        burning device time are not — the wire optimizes for the
        latter."""
        try:
            r, _, _ = select.select([self.connection], [], [], 0)
            if not r:
                return False
            return self.connection.recv(1, socket.MSG_PEEK) == b""
        except OSError:
            return True

    def _await_pending(self, pending, name, deadline_ms=None,
                       poll_s=0.05):
        """Block on a :class:`~.batcher.PendingResult` while watching
        the client socket; a disconnect cancels the queued request
        (counted in ``mxnet_serving_cancelled_total``) and raises
        :class:`~.admission.ClientDisconnected`."""
        backstop = time.monotonic() + (
            (deadline_ms or 120000.0) / 1000.0 + 10.0)
        while not pending._req.event.wait(poll_s):
            if self._client_gone():
                pending.cancel()
                raise ClientDisconnected(
                    f"client of {name!r} disconnected while queued")
            if time.monotonic() > backstop:
                break
        return pending.result()

    def _start_chunked(self, code=200, extra_headers=None):
        """Begin a ``Transfer-Encoding: chunked`` response (streamed
        session decode): headers out now, body arrives one
        ``_write_chunk`` per decode step."""
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()

    def _write_chunk(self, obj):
        """One JSON line as one HTTP chunk.  ``serving.stream_write``
        fires per chunk — an injected fault here is a client-side
        connection loss and must cancel the stream, not wedge it."""
        fault.inject("serving.stream_write")
        data = json.dumps(obj).encode() + b"\n"
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _end_chunked(self):
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()


class _Handler(JSONRequestHandler):

    # -- routes -------------------------------------------------------

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            return self._healthz()
        if path == "/metrics":
            return self._send(200, self.app.metrics.render().encode(),
                              content_type="text/plain; version=0.0.4")
        if path == "/v1/models":
            return self._send(200, {"models": self.app.repository.models()})
        if path == "/v1/trace":
            return self._trace_dump("server")
        if path == "/v1/flight":
            return self._flight_dump("server")
        self._send(404, {"error": "NotFound", "message": path})

    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/models/") and ":" in path:
            name, _, verb = path[len("/v1/models/"):].rpartition(":")
            handler = {"predict": self._predict, "load": self._load,
                       "unload": self._unload,
                       "reload": self._reload}.get(verb)
            if handler is not None and name:
                return handler(name)
        parsed = self.parse_session_path(path)
        if parsed is not None:
            model, sid, verb = parsed
            if verb == "create" and sid is None:
                return self._session_create(model)
            if sid is not None:
                handler = {"step": self._session_step,
                           "close": self._session_close,
                           "adopt": self._session_adopt}.get(verb)
                if handler is not None:
                    return handler(model, sid)
        self._send(404, {"error": "NotFound", "message": path})

    # -- handlers -----------------------------------------------------

    def _healthz(self):
        code, body = health_body(self.app.repository, self.app.t_start,
                                 sessions=self.app.sessions)
        self._send(code, body)

    def _predict(self, name):
        t0 = time.monotonic()
        code, timing, payload, hdrs = 500, {}, None, None
        # born here, or adopted from the router's hop span via the
        # X-MXNET-TRACE header (garbled → ignored, absent → the local
        # head-sampling decision).  None for unsampled requests — the
        # whole per-request cost of tracing-off is this one call.
        tspan = trace.from_header(self.headers.get(trace.HEADER),
                                  "server.request", model=name)
        try:
            with trace.activate(tspan):
                code, timing, payload = self._predict_inner(name)
        except ClientDisconnected:
            code = 499   # counted, never sent — the socket is gone
            payload = None
        except ServingError as e:
            code = e.http_status
            hdrs = (self.app.retry_headers(name)
                    if code in (429, 503) else None)
            payload = e.payload()
        except fault.TransientFault as e:
            code = 503   # injected front-end fault: client may retry
            payload = {"error": "TransientFault", "message": str(e)}
            hdrs = self.app.retry_headers(name)
            flightrec.note_error("server", e)
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            code = 500
            payload = {"error": type(e).__name__, "message": str(e)}
            # a framework error crossed the server's top boundary: the
            # black box dumps (rate-limited, best-effort) — the 500
            # below still carries the original error
            flightrec.note_error("server", e)
        # record BEFORE sending: the moment the response bytes go out,
        # the client may scrape /metrics, and its own request must
        # already be counted.  Unknown-model 404s are not attributed
        # per-model: arbitrary client-supplied names must not grow the
        # metrics registry.
        if code != 404:
            e2e = (time.monotonic() - t0) * 1000.0
            self.app.metrics.record_request(
                name, code, e2e_ms=e2e,
                compute_ms=timing.get("compute_ms"),
                queue_ms=timing.get("queue_ms"),
                trace_id=tspan.trace_id if tspan is not None else None)
        if tspan is not None:
            tspan.set(code=code,
                      queue_ms=timing.get("queue_ms"),
                      compute_ms=timing.get("compute_ms"))
            tspan.finish(
                outcome="ok" if code == 200 else f"http_{code}")
            # echo the id so the client (and the router's hop span)
            # can fetch /v1/trace for exactly this request
            hdrs = dict(hdrs or {})
            hdrs[trace.HEADER] = trace.header_value(tspan)
        if payload is not None:
            self._send(code, payload, extra_headers=hdrs)

    def _predict_inner(self, name):
        """The predict body proper, run under the request's trace
        context; returns ``(code, timing, payload)`` — errors
        propagate to :meth:`_predict`'s HTTP mapping."""
        # resolve the model FIRST: every later error (400/5xx) is
        # then attributed to a registry-backed name, so arbitrary
        # client-supplied names cannot grow the metrics registry
        entry = self.app.repository.get(name)
        body = self._body()
        if "inputs" not in body or not isinstance(body["inputs"],
                                                  list):
            raise BadRequest('body needs "inputs": [tensor, ...]')
        specs = entry.predictor.meta["inputs"]
        if len(body["inputs"]) != len(specs):
            raise BadRequest(
                f"model {name!r} takes {len(specs)} inputs, got "
                f"{len(body['inputs'])}")
        try:
            arrs = tuple(
                onp.asarray(x, dtype=spec["dtype"])
                for x, spec in zip(body["inputs"], specs))
        except (TypeError, ValueError) as e:
            raise BadRequest(f"malformed input tensor: {e}")
        for a, spec in zip(arrs, specs):
            want = tuple(spec["shape"][1:])
            if tuple(a.shape) != want:
                raise BadRequest(
                    f"instance shape {tuple(a.shape)} != exported "
                    f"instance shape {want}")
        # async submit + disconnect-aware wait: a client that
        # hangs up while its request is queued gets it CANCELLED
        # (the flush worker drops the row before it costs device
        # time) instead of computing into a dead socket
        deadline = body.get("timeout_ms")
        pending = self.app.repository.predict_async(
            name, arrs, deadline)
        out, timing = self._await_pending(pending, name, deadline)
        import jax
        outputs = [o.tolist()
                   for o in jax.tree_util.tree_leaves(out)]
        payload = {"outputs": outputs,
                   "timing": {k: round(v, 3)
                              for k, v in timing.items()
                              if v is not None}}
        return 200, timing, payload

    def _admin(self, name, fn):
        # errors attribute to the name only when it names a loaded
        # model (a failed :load of an arbitrary name must not mint a
        # metrics entry); successes always do — :load just created it
        try:
            result = fn(self._body())
            self.app.metrics.record_request(name, 200)
            self._send(200, result)
        except ServingError as e:
            if e.http_status != 404 and self.app.repository.has(name):
                self.app.metrics.record_request(name, e.http_status)
            self._send(e.http_status, e.payload())
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            if self.app.repository.has(name):
                self.app.metrics.record_request(name, 500)
            self._send(500, {"error": type(e).__name__,
                             "message": str(e)})

    def _load(self, name):
        def fn(body):
            if "path" not in body:
                raise BadRequest('load needs {"path": artifact-prefix}')
            return self.app.repository.load(
                name, body["path"], version=body.get("version"),
                warmup=body.get("warmup"), slo=body.get("slo"))
        self._admin(name, fn)

    def _unload(self, name):
        self._admin(name, lambda body:
                    self.app.repository.unload(name))

    def _reload(self, name):
        def fn(body):
            return self.app.repository.reload(
                name, path=body.get("path"),
                version=body.get("version"),
                warmup=body.get("warmup"), slo=body.get("slo"))
        self._admin(name, fn)

    # -- stateful sessions (docs/serving.md "Sessions") ---------------

    def _session_guarded(self, model, fn):
        """Error→HTTP mapping for the session verbs: eviction/loss are
        410 Gone (typed, terminal for that id — retrying can never
        succeed), overload/drain keep the live-derived Retry-After."""
        code = 500
        tspan = trace.from_header(self.headers.get(trace.HEADER),
                                  "server.session", model=model)
        try:
            with trace.activate(tspan):
                fn()
            code = 200
        except ClientDisconnected:
            code = 499               # counted, nothing sendable
        except (SessionExpiredError, SessionLostError) as e:
            code = 410
            self._send(410, {"error": type(e).__name__,
                             "message": str(e)})
        except ServingError as e:
            code = e.http_status
            hdrs = (self.app.retry_headers(model)
                    if code in (429, 503) else None)
            self._send(code, e.payload(), extra_headers=hdrs)
        except fault.TransientFault as e:
            code = 503
            self._send(503, {"error": "TransientFault",
                             "message": str(e)},
                       extra_headers=self.app.retry_headers(model))
        except Exception as e:  # mxlint: allow-broad-except(HTTP boundary: any error becomes a 500 response)
            code = 500
            self._send(500, {"error": type(e).__name__,
                             "message": str(e)})
        if tspan is not None:
            tspan.set(code=code)
            tspan.finish(
                outcome="ok" if code == 200 else f"http_{code}")
        if model in self.app.sessions.names():
            self.app.metrics.record_request(model, code)

    def _session_create(self, model):
        def fn():
            body = self._body()
            mgr = self.app.sessions.get(model)
            self._send(200, mgr.create(body.get("session_id")))
        self._session_guarded(model, fn)

    def _session_close(self, model, sid):
        def fn():
            self._send(200, self.app.sessions.get(model).close(sid))
        self._session_guarded(model, fn)

    def _session_adopt(self, model, sid):
        """Adopt a session from its latest snapshot (the migration
        verb the fleet router drives after a replica death)."""
        def fn():
            self._send(200, self.app.sessions.get(model).restore(sid))
        self._session_guarded(model, fn)

    def _session_step(self, model, sid):
        def fn():
            body = self._body()
            if "inputs" not in body or not isinstance(body["inputs"],
                                                      list):
                raise BadRequest('body needs "inputs": [tensor, ...]')
            mgr = self.app.sessions.get(model)
            arrs = tuple(body["inputs"])  # dtypes land in check_inputs
            steps = body.get("steps", 1)
            deadline = body.get("timeout_ms")
            if body.get("stream"):
                return self._session_stream(mgr, sid, arrs, steps,
                                            deadline)
            chunks, timing = mgr.step(sid, arrs, steps=steps,
                                      deadline_ms=deadline)
            self._send(200, {
                "session_id": sid, "steps": timing["steps"],
                "outputs": [[onp.asarray(leaf).tolist()
                             for leaf in chunk] for chunk in chunks],
                "timing": {k: round(v, 3)
                           for k, v in timing.items()
                           if v is not None}})
        self._session_guarded(model, fn)

    def _session_stream(self, mgr, sid, arrs, steps, deadline):
        """Chunked-response decode: one JSON line per decode step the
        moment it lands, a final ``done`` (or in-band ``error``) line,
        then the terminating chunk.  Concatenating the per-line
        outputs is bitwise-identical to the non-streamed response
        (the streaming-parity contract).  A broken pipe cancels the
        stream at the next step boundary — dead clients must not keep
        riding the batch."""
        handle = mgr.step(sid, arrs, steps=steps, deadline_ms=deadline,
                          stream=True)
        budget_s = ((deadline or 120000.0) / 1000.0 + 10.0)
        self._start_chunked(200)
        try:
            while True:
                try:
                    kind, payload = handle.chunk_queue.get(
                        timeout=budget_s)
                except _queue.Empty:
                    handle.cancel()
                    self._write_chunk({
                        "error": "DeadlineExceeded",
                        "message": "decode loop stalled",
                        "steps": handle.steps_done})
                    break
                if kind == "chunk":
                    self._write_chunk({
                        "session_id": sid,
                        "outputs": [onp.asarray(leaf).tolist()
                                    for leaf in payload]})
                elif kind == "done":
                    self._write_chunk({
                        "done": True, "session_id": sid,
                        "steps": payload["steps"],
                        "timing": {k: round(v, 3)
                                   for k, v in payload.items()
                                   if v is not None}})
                    break
                else:   # in-band typed error: stream ends, no restart
                    self._write_chunk({
                        "error": type(payload).__name__,
                        "message": str(payload),
                        "steps": handle.steps_done})
                    break
            self._end_chunked()
        except OSError as e:
            # broken pipe / reset / injected serving.stream_write
            # fault: the client is gone — stop decoding for it
            handle.cancel()
            raise ClientDisconnected(
                f"stream client of {mgr.name!r}/{sid} vanished: "
                f"{type(e).__name__}") from e


class InferenceServer:
    """Own the repository + metrics + HTTP listener as one unit."""

    def __init__(self, repository=None, host="127.0.0.1", port=0,
                 metrics=None):
        # adopt the repository's metrics when it already has one, so
        # handler-side counters and batcher-side counters land in the
        # same instance; otherwise rebind the repository (and its live
        # batchers) to ours
        if metrics is None and repository is not None:
            metrics = repository.metrics
        self.metrics = metrics or ServingMetrics()
        self.repository = repository or ModelRepository(
            metrics=self.metrics)
        if self.repository.metrics is not self.metrics:
            self.repository.set_metrics(self.metrics)
        else:
            self.metrics.attach_repository(self.repository)
        # stateful sessions share the repository's admission policy
        # (one drain drains both) and the server's metrics instance
        from .sessions import SessionHost
        self.sessions = SessionHost(
            metrics=self.metrics,
            admission=self.repository.admission,
            snapshot_dir=get_env("MXNET_SERVING_SESSION_DIR", None))
        self.metrics.register_with_profiler()
        self.host = host
        self.port = int(port)
        self.t_start = time.monotonic()
        self._httpd = None
        self._thread = None

    def retry_headers(self, model=None):
        """Live-state ``Retry-After`` for 429/503 responses: current
        queue depth times the observed per-request service time."""
        from .admission import retry_after_s
        depth = sum(self.repository.queue_depths().values())
        depth += sum(self.sessions.queue_depths().values())
        return {"Retry-After": retry_after_s(
            depth, self.metrics.service_ms_estimate(model))}

    def start(self):
        """Bind + serve on a background thread; returns the bound port
        (ephemeral when constructed with port=0)."""
        self._httpd = ServingHTTPServer((self.host, self.port),
                                        _Handler)
        self._httpd.app = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self, drain=True, timeout=30.0):
        """Graceful stop: drain queues first so queued requests get
        real responses (session streams truncate typed and every
        session snapshots, so migration after a drain is lossless),
        then close the listener."""
        if drain:
            self.repository.drain_all(timeout)
            self.sessions.drain_all(timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.metrics.unregister_from_profiler()


def main(argv=None):
    import argparse
    import signal

    p = argparse.ArgumentParser(
        description="mxnet-tpu dynamic-batching inference server")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PREFIX",
                   help="load artifact PREFIX as model NAME at startup")
    p.add_argument("--session-model", action="append", default=[],
                   metavar="NAME=SPEC",
                   help="register a stateful session model from the "
                        "sessions.SESSION_MODELS registry (e.g. "
                        "toy_decoder:dim=16,max_len=32)")
    p.add_argument("--session-dir", default=None,
                   help="shared CRC'd snapshot directory (overrides "
                        "MXNET_SERVING_SESSION_DIR); required for "
                        "cross-replica session migration")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int,
                   default=get_env("MXNET_SERVING_PORT", 8080, int))
    p.add_argument("--no-warmup", action="store_true",
                   help="skip per-bucket warmup compiles at load")
    args = p.parse_args(argv)

    # black box: name this process in flight dumps and arm the SIGUSR2
    # wedge-dump path (docs/observability.md "Flight recorder")
    flightrec.install_signal_handler(proc="server")
    server = InferenceServer(host=args.host, port=args.port)
    if args.session_dir:
        server.sessions.snapshot_dir = args.session_dir
    for spec in args.model:
        name, sep, path = spec.partition("=")
        if not sep:
            p.error(f"--model wants NAME=PREFIX, got {spec!r}")
        server.repository.load(name, path,
                               warmup=not args.no_warmup)
        print(f"[serving] loaded {name} from {path}", flush=True)
    for spec in args.session_model:
        name, sep, model_spec = spec.partition("=")
        if not sep:
            p.error(f"--session-model wants NAME=SPEC, got {spec!r}")
        server.sessions.add(name, model_spec,
                            warmup=not args.no_warmup)
        print(f"[serving] session model {name} = {model_spec}",
              flush=True)
    port = server.start()
    flightrec.record(flightrec.LIFECYCLE, "server.started", port=port,
                     models=sorted(server.repository.models()))
    print(f"[serving] listening on {args.host}:{port}", flush=True)

    done = threading.Event()

    def stop(signum, frame):
        print(f"[serving] signal {signum}: draining", flush=True)
        done.set()

    signal.signal(signal.SIGTERM, stop)
    signal.signal(signal.SIGINT, stop)
    done.wait()
    server.shutdown(drain=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
