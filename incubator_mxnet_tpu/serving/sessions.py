"""Server-side stateful sessions with crash-safe carry.

The stateless batcher serves the easy workload; the traffic that
dominates real fleets — chat sessions, autoregressive decode, streams —
needs *state on the server*: a per-session carry tree (KV-cache-style
for sequence models) that every decode step reads and replaces.  That
state is what makes robustness hard: the carry lives in exactly one
replica's memory, so replica death, rolling reloads and TTL expiry all
need defined, typed outcomes.  This module is that contract:

* :class:`SessionModel` — a batched decode step ``step_fn(carry, x) ->
  (carry, y)`` jitted through the unified
  :class:`~..executor_cache.Executor` choke point, with a per-row carry
  template and per-step input specs.  Warmup pre-compiles one
  executable per padding bucket, so decode steps never compile
  (``mxnet_serving_compile_total`` stays flat across session
  join/leave).
* :class:`SessionManager` — owns the sessions of one model:
  ``create`` / ``step`` / ``close`` verbs, idle-TTL + bounded-count
  eviction (typed :class:`~..error.SessionExpiredError`), and a
  :class:`~.batcher.ContinuousBatcher` running the shared decode loop.
* **Snapshots** — every ``MXNET_SERVING_SESSION_SNAPSHOT_STEPS`` steps
  (and synchronously at drain) a session's carry is written through
  :class:`~..checkpoint.AsyncCheckpointManager` — the same CRC-per-
  shard, atomic-rename, newest-first-fallback format training
  checkpoints use.  ``restore()`` rebuilds a session from its latest
  valid snapshot on ANY replica sharing the directory; a session with
  no recoverable snapshot raises typed
  :class:`~..error.SessionLostError`.  Never a hang, never a silently
  restarted stream.

Determinism contract (asserted in tests/test_sessions.py): the decode
step is row-independent and batch-size-stable, so a session's output
stream is bitwise identical whether it decodes alone, rides a full
bucket, or resumes from a snapshot on another replica.

Fault points: ``serving.session_step`` (fired per decode step, inside
the batcher's retry), ``serving.session_snapshot`` (before each
snapshot write; failures are counted, never fatal to the stream).
"""
from __future__ import annotations

import logging
import os
import shutil
import threading
import time
import uuid

import numpy as onp

from ..base import get_env
from .. import fault, flightrec
from ..error import SessionExpiredError, SessionLostError
from ..locks import named_condition, named_lock
from .admission import (Admission, BadRequest, ModelNotFound,
                        ServingError, ShuttingDown)
from .batcher import ContinuousBatcher, parse_buckets
from .metrics import Histogram

__all__ = ["SessionModel", "SessionManager", "SessionHost",
           "SessionNotFound", "SESSION_MODELS", "build_session_model",
           "toy_decoder"]

_log = logging.getLogger("incubator_mxnet_tpu.serving.sessions")


class SessionNotFound(ServingError):
    """No session with that id on this manager (never created here, or
    already closed).  404 — distinct from the typed eviction/loss
    errors, which are 410 (the id existed and is gone forever)."""
    http_status = 404


# ---------------------------------------------------------------------------
# session model: a batched decode step behind the Executor choke point
# ---------------------------------------------------------------------------

class SessionModel:
    """One decode-step program + the carry/input signature around it.

    ``step_fn(carry, x) -> (carry, y)`` is *batched*: every leaf of
    ``carry`` and every array in ``x`` has a leading batch dim (the
    bucket size).  ``carry_template`` is ONE ROW — the fresh-session
    carry (position 0, zeroed caches); ``input_specs`` is the per-step
    per-row input signature ``[(shape, dtype), ...]``.
    """

    def __init__(self, name, step_fn, carry_template, input_specs,
                 spec=None):
        import jax
        from ..executor_cache import Executor
        self.name = name
        self.spec = spec                   # rebuildable description
        leaves, treedef = jax.tree_util.tree_flatten(carry_template)
        self._treedef = treedef
        self._template_rows = [onp.asarray(v) for v in leaves]
        self.input_specs = [(tuple(sh), onp.dtype(dt))
                            for sh, dt in input_specs]
        self._zero_inputs = tuple(onp.zeros(sh, dt)
                                  for sh, dt in self.input_specs)
        # donate the stacked carry: the step's output carry has the
        # same shapes, so XLA reuses the buffers and a decode step
        # allocates only its outputs
        self._executor = Executor(step_fn, site=f"session:{name}",
                                  donate_argnums=(0,))

    # -- carry plumbing ----------------------------------------------

    def fresh_carry(self):
        """One new session's carry row (leaf list, copied)."""
        return [onp.array(v) for v in self._template_rows]

    def carry_from_flat(self, flat):
        """Rebuild a carry row from a snapshot's ``{leaf_i: array}``
        dict (restore path)."""
        keys = sorted(flat)
        want = len(self._template_rows)
        if len(keys) != want:
            raise SessionLostError(
                f"snapshot for a {self.name!r} session carries "
                f"{len(keys)} leaves, model wants {want}")
        return [onp.asarray(flat[k]) for k in keys]

    def flat_of_carry(self, rows):
        return {f"leaf_{i:03d}": onp.asarray(v)
                for i, v in enumerate(rows)}

    def check_inputs(self, arrs):
        if len(arrs) != len(self.input_specs):
            raise BadRequest(
                f"session model {self.name!r} takes "
                f"{len(self.input_specs)} step inputs, got {len(arrs)}")
        out = []
        for a, (sh, dt) in zip(arrs, self.input_specs):
            a = onp.asarray(a, dtype=dt)
            if tuple(a.shape) != sh:
                raise BadRequest(
                    f"step input shape {tuple(a.shape)} != session "
                    f"model instance shape {sh}")
            out.append(a)
        return tuple(out)

    # -- batched execution -------------------------------------------

    def _stack(self, rows_list, pad_rows, padded_to):
        # HOST-side stack: carry rows live as numpy (views of the
        # previous step's device->host pull), so a decode step costs
        # O(leaves) device transfers, not O(rows x leaves) jax
        # dispatches — per-row jnp slicing/stacking was measured to
        # eat the entire continuous-batching win on CPU
        n = len(rows_list)
        stacked = []
        for j in range(len(pad_rows)):
            cols = [rows[j] for rows in rows_list]
            cols += [pad_rows[j]] * (padded_to - n)
            stacked.append(onp.stack(cols))
        return stacked

    def step_batch(self, carries, inputs, padded_to):
        """Run one decode step over ``len(carries)`` live rows padded
        to ``padded_to``; returns (per-row new carries, per-row output
        leaf lists — numpy views of the batched result).  The
        signature seen by jit depends only on ``padded_to`` — the
        bucket set is the whole compile universe.
        """
        import jax
        n = len(carries)
        carry_stack = self._treedef.unflatten(
            self._stack(carries, self._template_rows, padded_to))
        x_stack = tuple(self._stack(
            [list(x) for x in inputs], list(self._zero_inputs),
            padded_to))
        new_carry, y = self._executor(carry_stack, x_stack)
        new_leaves = [onp.asarray(leaf)
                      for leaf in jax.tree_util.tree_leaves(new_carry)]
        y_leaves = [onp.asarray(leaf)
                    for leaf in jax.tree_util.tree_leaves(y)]
        new_rows = [[leaf[i] for leaf in new_leaves] for i in range(n)]
        out_rows = [[leaf[i] for leaf in y_leaves] for i in range(n)]
        return new_rows, out_rows

    def warmup(self, buckets):
        """Pre-compile one decode executable per padding bucket, so no
        live stream ever pays an XLA compile."""
        for b in sorted(set(buckets)):
            self.step_batch([self.fresh_carry()],
                            [self._zero_inputs], int(b))
        return self.compile_count

    @property
    def compile_count(self):
        return self._executor.compile_count


# ---------------------------------------------------------------------------
# builtin session models (CLI / process replicas / bench)
# ---------------------------------------------------------------------------

def toy_decoder(dim=16, max_len=32, seed=0):
    """Single-head autoregressive attention decoder with a fixed-shape
    KV cache — the reference session workload.

    Carry per row: ``k``/``v`` caches ``(max_len, dim)``, write
    position ``pos`` (clamped to the last slot past ``max_len``), and
    the previous output ``y``.  Each step writes a fresh K/V at
    ``pos`` and attends over the ``pos+1`` live entries — the
    single-query specialization of the streaming-softmax block in
    :func:`..parallel.ring_attention._local_block` (same max-subtract
    flash-attention algebra), restated in **batch-invariant** ops:
    every contraction is a broadcast-multiply + fixed-axis reduce
    instead of a ``dot``, because XLA lowers dots differently per
    batch size (ULP-level drift) while a per-row middle-axis reduce
    keeps one reduction order regardless of how many rows ride the
    bucket.  That makes batched decode bitwise-equal to solo decode —
    the continuous-batching correctness contract this module's tests
    pin.
    """
    import jax.numpy as jnp

    dim, max_len, seed = int(dim), int(max_len), int(seed)
    rng = onp.random.RandomState(seed)

    def w():
        return (rng.randn(dim, dim) * (1.0 / dim ** 0.5)).astype(
            onp.float32)

    Wx, Wh, Wq, Wk, Wv, Wo = w(), w(), w(), w(), w(), w()
    scale = 1.0 / (dim ** 0.5)

    def mm(x, W):
        # (B, D) x (D, E) with a per-row reduction order independent
        # of B — the batch-invariance trick (see class docstring)
        return (x[:, :, None] * W[None, :, :]).sum(axis=1)

    def step_fn(carry, x):
        (x,) = x
        B = x.shape[0]
        h = jnp.tanh(mm(carry["y"], Wh) + mm(x, Wx))
        q, k_new, v_new = mm(h, Wq), mm(h, Wk), mm(h, Wv)
        rows = jnp.arange(B)
        K = carry["k"].at[rows, carry["pos"]].set(k_new)
        V = carry["v"].at[rows, carry["pos"]].set(v_new)
        live = carry["pos"] + 1
        mask = jnp.arange(max_len)[None, :] < live[:, None]
        logits = (q[:, None, :] * K).sum(axis=-1) * scale
        logits = jnp.where(mask, logits, -jnp.inf)
        m = jnp.max(logits, axis=-1, keepdims=True)  # >= 1 live entry
        p = jnp.where(mask, jnp.exp(logits - m), 0.0)
        attn = ((p[:, :, None] * V).sum(axis=1)
                / p.sum(axis=-1, keepdims=True))
        y = jnp.tanh(mm(attn, Wo))
        new = {"k": K, "v": V, "y": y,
               "pos": jnp.minimum(live, max_len - 1)}
        return new, y

    template = {"k": onp.zeros((max_len, dim), onp.float32),
                "v": onp.zeros((max_len, dim), onp.float32),
                "y": onp.zeros((dim,), onp.float32),
                "pos": onp.zeros((), onp.int32)}
    return SessionModel(
        "toy_decoder", step_fn, template,
        input_specs=[((dim,), onp.float32)],
        spec=f"toy_decoder:dim={dim},max_len={max_len},seed={seed}")


#: Named session-model builders — the registry the server CLI /
#: process replicas build from (``--session-model name=spec``): a
#: subprocess cannot be handed a live python step function, only a
#: spec string it can rebuild one from.
SESSION_MODELS = {"toy_decoder": toy_decoder}


def build_session_model(spec):
    """``"toy_decoder"`` or ``"toy_decoder:dim=8,max_len=16"`` →
    :class:`SessionModel` via the :data:`SESSION_MODELS` registry."""
    kind, _, opts = str(spec).partition(":")
    builder = SESSION_MODELS.get(kind)
    if builder is None:
        raise ValueError(
            f"unknown session model {kind!r} (registered: "
            f"{', '.join(sorted(SESSION_MODELS))})")
    kw = {}
    for opt in filter(None, (o.strip() for o in opts.split(","))):
        k, sep, v = opt.partition("=")
        if not sep:
            raise ValueError(
                f"session model option {opt!r} in {spec!r}: want k=v")
        kw[k] = float(v) if "." in v else int(v)
    model = builder(**kw)
    model.spec = spec
    return model


# ---------------------------------------------------------------------------
# session manager
# ---------------------------------------------------------------------------

class _Session:
    __slots__ = ("sid", "carry", "steps", "t_created", "t_last",
                 "busy", "closed", "snapshot_step", "t_snapshot",
                 "ckpt")

    def __init__(self, sid, carry, steps=0):
        now = time.monotonic()
        self.sid = sid
        self.carry = carry          # leaf-row list, owner: manager
        self.steps = int(steps)
        self.t_created = now
        self.t_last = now
        self.busy = False           # checked out by the decode loop
        self.closed = False
        self.snapshot_step = int(steps)   # restored == snapshotted
        self.t_snapshot = now
        self.ckpt = None            # lazy AsyncCheckpointManager


class SessionManager:
    """Sessions of one model: create/step/close, eviction, snapshots.

    One :class:`~.batcher.ContinuousBatcher` per manager runs the
    shared decode loop; the manager owns every carry and hands rows to
    the loop via ``checkout``/``writeback``/``release`` so a carry is
    never concurrently stepped and snapshotted (snapshots land at step
    boundaries — the crash-consistency point).
    """

    def __init__(self, name, model, metrics=None, admission=None,
                 snapshot_dir=None, snapshot_steps=None, ttl_s=None,
                 max_sessions=None, buckets=None, max_batch=None,
                 warmup=True):
        self.name = name
        self.model = model
        self.metrics = metrics
        self.admission = admission or Admission()
        self.snapshot_dir = (
            snapshot_dir if snapshot_dir is not None
            else get_env("MXNET_SERVING_SESSION_DIR", None))
        self.snapshot_steps = int(
            snapshot_steps if snapshot_steps is not None
            else get_env("MXNET_SERVING_SESSION_SNAPSHOT_STEPS", 16,
                         int))
        self.ttl_s = float(
            ttl_s if ttl_s is not None
            else get_env("MXNET_SERVING_SESSION_TTL_S", 600.0, float))
        self.max_sessions = int(
            max_sessions if max_sessions is not None
            else get_env("MXNET_SERVING_SESSION_MAX", 256, int))
        self.max_stream_steps = get_env(
            "MXNET_SERVING_SESSION_MAX_STEPS", 1024, int)
        if self.max_sessions < 1 or self.snapshot_steps < 1:
            raise ValueError(
                "MXNET_SERVING_SESSION_MAX and "
                "MXNET_SERVING_SESSION_SNAPSHOT_STEPS must be >= 1")
        self.buckets = (list(buckets) if buckets is not None
                        else parse_buckets())
        self._sessions: dict[str, _Session] = {}
        self._expired: dict[str, str] = {}   # sid -> reason (bounded)
        self._evicted_dirs: list[str] = []   # snapshot trees to drop
        self._lock = named_lock("sessions.registry")
        self.stream_ms = Histogram()
        self._counters = {"steps": 0, "created": 0, "evicted": 0,
                          "snapshots": 0, "snapshot_failures": 0,
                          "restored": 0, "restore_retries": 0}
        # periodic snapshots run on a dedicated thread so the decode
        # loop NEVER does IO (measured: in-loop snapshots halve decode
        # throughput); carry rows are immutable once written back, so
        # the snapshotter works from a consistent (carry, steps) pair
        # grabbed under the lock
        self._snap_cond = named_condition("sessions.snapshot")
        self._snap_due: list[str] = []
        self._snap_stop = False
        self._snapshotter = None
        if self.snapshot_dir is not None:
            self._snapshotter = threading.Thread(
                target=self._snapshot_loop,
                name=f"session-snap-{name}", daemon=True)
            self._snapshotter.start()
        self.batcher = ContinuousBatcher(
            name, model.step_batch, owner=self, buckets=self.buckets,
            max_batch=max_batch, metrics=metrics)
        if warmup:
            sizes = sorted({b for b in self.buckets
                            if b <= self.batcher.max_batch}
                           | {self.batcher._bucket_for(
                               self.batcher.max_batch)})
            model.warmup(sizes)

    # -- verbs --------------------------------------------------------

    def create(self, session_id=None):
        """New session with a fresh carry; returns its describe dict.
        Past ``max_sessions`` the least-recently-used idle session is
        evicted (its next use raises typed ``SessionExpiredError``)."""
        self.sweep()
        if self.admission.draining:
            raise ShuttingDown(
                f"session model {self.name!r} is draining")
        sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
        try:
            with self._lock:
                if sid in self._sessions:
                    raise ServingError(
                        f"session {sid!r} already exists")
                while len(self._sessions) >= self.max_sessions:
                    victim = min(
                        (s for s in self._sessions.values()
                         if not s.busy),
                        key=lambda s: s.t_last, default=None)
                    if victim is None:
                        from .admission import QueueFullError
                        raise QueueFullError(
                            f"session table for {self.name!r} is "
                            f"full ({self.max_sessions}) and every "
                            "session is mid-stream")
                    self._evict_locked(victim.sid,
                                       "evicted (session cap reached)")
                s = _Session(sid, self.model.fresh_carry())
                self._sessions[sid] = s
                self._expired.pop(sid, None)
                self._counters["created"] += 1
                flightrec.record(flightrec.SESSION, "session.created",
                                 model=self.name, sid=sid)
        finally:
            self._cleanup_evicted()
        return self.describe_session(sid)

    def step(self, sid, inputs, steps=1, deadline_ms=None,
             stream=False):
        """Run ``steps`` decode steps for ``sid`` through the shared
        continuous batcher.  Returns ``(chunks, timing)``, or the
        :class:`~.batcher.StreamResult` handle when ``stream=True``
        (chunks then arrive on its queue as they decode)."""
        steps = int(steps)
        if not 1 <= steps <= self.max_stream_steps:
            raise BadRequest(
                f"steps must be in [1, {self.max_stream_steps}], got "
                f"{steps}")
        arrs = self.model.check_inputs(inputs)
        self._peek(sid)   # fail fast with the typed error pre-queue
        handle = self.batcher.submit(
            sid, arrs, n_steps=steps,
            deadline_ms=self.admission.deadline_ms(deadline_ms),
            admit=self.admission.gate(self.name), stream=stream)
        if stream:
            return handle
        return handle.result()

    def close(self, sid):
        """Forget the session and its snapshots.  A close while a
        stream is queued/decoding truncates it typed at the next step
        boundary."""
        with self._lock:
            s = self._sessions.pop(sid, None)
            if s is None:
                self._raise_gone(sid)
            s.closed = True
            self._remember_expired(sid, "closed")
        self._drop_snapshots(sid)
        return {"session_id": sid, "closed": True, "steps": s.steps}

    def _peek(self, sid):
        """Fail fast with the typed gone/expired error before a step
        even queues (the batcher's checkout re-checks at admission)."""
        try:
            with self._lock:
                s = self._sessions.get(sid)
                if s is None:
                    self._raise_gone(sid)
                if self._ttl_expired(s):
                    self._evict_locked(sid, "idle TTL expired")
                    self._raise_gone(sid)
        finally:
            self._cleanup_evicted()

    # -- carry lifecycle (called by the ContinuousBatcher worker) -----

    def checkout(self, sid):
        try:
            with self._lock:
                s = self._sessions.get(sid)
                if s is None:
                    self._raise_gone(sid)
                if self._ttl_expired(s):
                    self._evict_locked(sid, "idle TTL expired")
                    self._raise_gone(sid)
                s.busy = True
                return s.carry
        finally:
            self._cleanup_evicted()

    def writeback(self, sid, carry, step_ms):
        """Land one decode step's new carry — the state every snapshot
        and migration is based on.  Returns the session-absolute step
        count (surfaced to clients so a migration's snapshot re-base
        is *visible*, never silent).  Raises typed when the session
        was closed mid-stream (the stream truncates)."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None or s.closed:
                raise SessionExpiredError(
                    f"session {sid!r} on {self.name!r} was closed "
                    "mid-stream")
            s.carry = carry
            s.steps += 1
            steps = s.steps
            s.t_last = time.monotonic()
            self._counters["steps"] += 1
            due = (self.snapshot_dir is not None
                   and s.steps - s.snapshot_step >= self.snapshot_steps)
        self.stream_ms.observe(step_ms)
        if due:
            with self._snap_cond:
                if sid not in self._snap_due:
                    self._snap_due.append(sid)
                    self._snap_cond.notify()
        return steps

    def release(self, sid):
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None:
                s.busy = False
                s.t_last = time.monotonic()

    # -- snapshots / restore ------------------------------------------

    def _ckpt_of(self, s):
        from ..checkpoint import AsyncCheckpointManager
        if s.ckpt is None:
            s.ckpt = AsyncCheckpointManager(
                os.path.join(self.snapshot_dir, self.name, s.sid),
                keep=2)
        return s.ckpt

    def _snapshot_loop(self):
        """Dedicated snapshot worker: drains the due list, keeping IO
        off the decode loop entirely."""
        while True:
            with self._snap_cond:
                while not self._snap_due and not self._snap_stop:
                    self._snap_cond.wait()
                if self._snap_stop and not self._snap_due:
                    return
                sid = self._snap_due.pop(0)
            with self._lock:
                s = self._sessions.get(sid)
            if s is not None:
                self._snapshot(s)

    def _snapshot(self, s, sync=False):
        """CRC'd carry snapshot of a step boundary.  ``(carry,
        steps)`` is grabbed atomically under the lock — carry rows are
        never mutated in place, so the pair stays consistent while the
        decode loop races ahead.  Failures are counted and logged,
        never fatal: the stream keeps decoding and the next period
        retries — a lost snapshot only widens the window a migration
        re-bases over."""
        with self._lock:
            rows, steps = s.carry, s.steps
        try:
            fault.inject("serving.session_snapshot",
                         f"{self.name}:{s.sid}")
            ckpt = self._ckpt_of(s)
            ckpt.save(steps, self.model.flat_of_carry(rows),
                      wait=sync)
            with self._lock:
                s.snapshot_step = max(s.snapshot_step, steps)
                s.t_snapshot = time.monotonic()
                self._counters["snapshots"] += 1
        except Exception as e:  # mxlint: allow-broad-except(a failed snapshot must never kill the live stream — counted, logged, retried next period)
            with self._lock:
                self._counters["snapshot_failures"] += 1
            _log.warning("session %s/%s: snapshot at step %d failed: "
                         "%s: %s", self.name, s.sid, steps,
                         type(e).__name__, e)

    def snapshot_all(self, sync=True):
        """Snapshot every live session (drain path: a migration after
        a clean drain continues from the CURRENT step, losslessly).
        With ``sync`` this also AWAITS snapshots the background
        snapshotter already dispatched — "drained" must mean durable,
        not merely scheduled."""
        if self.snapshot_dir is None:
            return 0
        with self._lock:
            sessions = list(self._sessions.values())
        live = [s for s in sessions if s.steps > s.snapshot_step]
        for s in live:
            self._snapshot(s, sync=sync)
        if sync:
            for s in sessions:
                if s in live or s.ckpt is None:
                    continue
                try:
                    s.ckpt.wait()
                except Exception as e:  # mxlint: allow-broad-except(a failed in-flight snapshot write is counted like any snapshot failure — the drain itself must not die on it)
                    with self._lock:
                        self._counters["snapshot_failures"] += 1
                    _log.warning("session %s/%s: in-flight snapshot "
                                 "failed at drain: %s: %s", self.name,
                                 s.sid, type(e).__name__, e)
        return len(live)

    def restore(self, sid):
        """Adopt a session from its latest valid snapshot (written by
        this replica or any other sharing ``snapshot_dir``).  The
        rebuilt carry is bitwise the snapshotted one — continuation is
        bitwise-equal to an unbroken run from that snapshot.  No
        usable snapshot ⇒ typed :class:`~..error.SessionLostError`."""
        with self._lock:
            live = sid in self._sessions
        if live:
            # idempotent adopt: a retried adopt whose first response
            # was lost must not fail — the live carry here is at
            # least as new as any snapshot
            return self.describe_session(sid)
        if self.snapshot_dir is None:
            raise SessionLostError(
                f"session {sid!r} cannot be restored: no "
                "MXNET_SERVING_SESSION_DIR snapshot directory is "
                "configured")
        from ..checkpoint import AsyncCheckpointManager
        d = os.path.join(self.snapshot_dir, self.name, sid)
        if not os.path.isdir(d):
            raise SessionLostError(
                f"session {sid!r} has no snapshot under {d} — its "
                "replica died before the first snapshot period")
        try:
            ckpt = AsyncCheckpointManager(d, keep=2)
            flat, steps = self._restore_newest(ckpt, d)
        except SessionLostError:
            raise
        except Exception as e:  # mxlint: allow-broad-except(every restore failure — corrupt/missing/torn snapshots included — must surface as the ONE typed error the failover contract names)
            raise SessionLostError(
                f"session {sid!r} snapshot unusable: "
                f"{type(e).__name__}: {e}") from e
        carry = self.model.carry_from_flat(flat)
        with self._lock:
            # a racing adopt of the same sid: whoever landed first
            # wins (its carry may already be ahead of this snapshot)
            if sid not in self._sessions:
                s = _Session(sid, carry, steps=steps)
                s.ckpt = ckpt
                self._sessions[sid] = s
                self._expired.pop(sid, None)
                self._counters["restored"] += 1
                flightrec.record(flightrec.SESSION, "session.restored",
                                 model=self.name, sid=sid, steps=steps)
        return self.describe_session(sid)

    #: Restore-vs-snapshotter race budget (seconds).  A restore that
    #: fails while the SOURCE replica's async snapshotter is visibly
    #: mid-publish — a ``step_N.tmp`` staging dir in the session's
    #: snapshot tree, or the committed-step list changing between two
    #: attempts — retries within this window: the commit is one atomic
    #: rename away, and failing the adopt because we looked 5ms early
    #: was the known session-restore flake.  Failures with NO in-flight
    #: evidence still surface immediately (typed, no added latency).
    RESTORE_RACE_WAIT_S = 2.0

    def _restore_newest(self, ckpt, d):
        """Load the newest loadable committed snapshot in ``d``,
        newest-first past torn entries, retrying (bounded by
        :data:`RESTORE_RACE_WAIT_S`) when the failure coincides with a
        concurrent snapshot publish.  Walking newest-first OURSELVES
        keeps the restored step counter naming the snapshot that
        actually loaded — a fallback past a torn newest snapshot
        re-bases the session's step count along with its carry."""
        from ..error import CheckpointCorruptError
        deadline = time.monotonic() + self.RESTORE_RACE_WAIT_S
        prev_committed = None
        while True:
            committed = ckpt.all_steps()
            try:
                if not committed:
                    raise FileNotFoundError("no committed snapshot")
                flat, steps, last_err = None, None, None
                for step in reversed(committed):
                    try:
                        flat = ckpt.restore(step=step)
                        steps = step
                        break
                    except CheckpointCorruptError as e:
                        last_err = e
                if flat is None:
                    raise last_err
                return flat, steps
            except Exception:
                racing = self._snapshot_in_flight(d, committed,
                                                  prev_committed)
                prev_committed = committed
                if racing and time.monotonic() < deadline:
                    with self._lock:
                        self._counters["restore_retries"] += 1
                    time.sleep(0.05)
                    continue
                raise

    @staticmethod
    def _snapshot_in_flight(d, committed, prev_committed):
        """True when a concurrent snapshot publish is in evidence: a
        ``step_N.tmp`` staging dir (the async writer is mid-write, its
        atomic rename imminent), or the committed-step list moved
        between two restore attempts."""
        try:
            names = os.listdir(d)
        except OSError:
            return False
        if any(n.startswith("step_") and n.endswith(".tmp")
               for n in names):
            return True
        return (prev_committed is not None
                and committed != prev_committed)

    def _drop_snapshots(self, sid):
        if self.snapshot_dir is not None:
            shutil.rmtree(
                os.path.join(self.snapshot_dir, self.name, sid),
                ignore_errors=True)

    # -- eviction -----------------------------------------------------

    def _ttl_expired(self, s):
        return (not s.busy
                and time.monotonic() - s.t_last > self.ttl_s)

    def _evict_locked(self, sid, reason):
        self._sessions.pop(sid, None)
        self._remember_expired(sid, reason)
        self._counters["evicted"] += 1
        flightrec.record(flightrec.SESSION, "session.evicted",
                         severity="warn", model=self.name, sid=sid,
                         reason=reason)
        # snapshots die with the session (an evicted id must not be
        # resurrectable via :adopt, and churn must not leak disk) —
        # but rmtree is IO, so it runs after the lock is released
        self._evicted_dirs.append(sid)

    def _cleanup_evicted(self):
        """Drop evicted sessions' snapshot trees (called OUTSIDE the
        lock by every eviction site)."""
        while True:
            with self._lock:
                if not self._evicted_dirs:
                    return
                sid = self._evicted_dirs.pop()
            self._drop_snapshots(sid)

    def _remember_expired(self, sid, reason):
        self._expired[sid] = reason
        while len(self._expired) > 1024:
            self._expired.pop(next(iter(self._expired)))

    def _raise_gone(self, sid):
        reason = self._expired.get(sid)
        if reason is not None:
            raise SessionExpiredError(
                f"session {sid!r} on {self.name!r} is gone: {reason}")
        raise SessionNotFound(
            f"no session {sid!r} on model {self.name!r}")

    def sweep(self):
        """Evict idle-past-TTL sessions (run opportunistically on
        create/describe — eviction also happens lazily at checkout, so
        an unswept session can never serve stale)."""
        with self._lock:
            for sid in [sid for sid, s in self._sessions.items()
                        if self._ttl_expired(s)]:
                self._evict_locked(sid, "idle TTL expired")
        self._cleanup_evicted()

    # -- introspection -------------------------------------------------

    def describe_session(self, sid):
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                self._raise_gone(sid)
            now = time.monotonic()
            return {"session_id": sid, "model": self.name,
                    "steps": s.steps,
                    "age_s": round(now - s.t_created, 3),
                    "idle_s": round(now - s.t_last, 3),
                    "snapshot_step": s.snapshot_step,
                    "busy": s.busy}

    def describe(self):
        """The pinned JSON shape ``/healthz`` and tests rely on."""
        self.sweep()
        with self._lock:
            n = len(self._sessions)
            counters = dict(self._counters)
        return {"model": self.name,
                "spec": self.model.spec,
                "state": "draining" if not self.batcher._running
                         else "ready",
                "active_sessions": n,
                "active_streams": self.batcher.active_streams,
                "queue_depth": self.batcher.depth,
                "steps_total": counters["steps"],
                "snapshots": counters["snapshots"],
                "snapshot_failures": counters["snapshot_failures"],
                "evicted": counters["evicted"],
                "restored": counters["restored"],
                "compile_count": self.model.compile_count,
                "buckets": list(self.buckets),
                "snapshot_steps": self.snapshot_steps,
                "ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions}

    def stats(self):
        """Flat gauge view for metrics/profiler exposition."""
        with self._lock:
            n = len(self._sessions)
            counters = dict(self._counters)
            oldest = max(
                (time.monotonic() - s.t_snapshot
                 for s in self._sessions.values()
                 if s.steps > 0), default=0.0)
        out = {"active_sessions": n,
               "steps_total": counters["steps"],
               "snapshots_total": counters["snapshots"],
               "snapshot_failures_total":
                   counters["snapshot_failures"],
               "evictions_total": counters["evicted"],
               "restored_total": counters["restored"],
               "snapshot_age_s": round(oldest, 3),
               "compile_count": self.model.compile_count,
               "stream_ms": self.stream_ms.snapshot()}
        return out

    # -- lifecycle ----------------------------------------------------

    def drain(self, timeout=30.0):
        """Stop the decode loop (active streams truncate typed at the
        next step boundary), retire the snapshotter, then snapshot
        every session synchronously — a post-drain migration is
        lossless."""
        self.batcher.drain(timeout)
        with self._snap_cond:
            self._snap_stop = True
            self._snap_cond.notify_all()
        if self._snapshotter is not None:
            self._snapshotter.join(timeout)
            self._snapshotter = None
        self.snapshot_all(sync=True)

    close_manager = drain


# ---------------------------------------------------------------------------
# session host: the per-process registry (server + thread replicas)
# ---------------------------------------------------------------------------

class SessionHost:
    """Session managers of one serving process, keyed by model name —
    the sessions-side twin of :class:`~.model_repository
    .ModelRepository` (shared admission, shared metrics)."""

    def __init__(self, metrics=None, admission=None, snapshot_dir=None,
                 buckets=None):
        self.metrics = metrics
        self.admission = admission or Admission()
        self.snapshot_dir = snapshot_dir
        self._buckets = buckets
        self._managers: dict[str, SessionManager] = {}
        self._lock = named_lock("sessions.store")
        if metrics is not None:
            metrics.attach_sessions(self)

    def add(self, name, model, **kw):
        """Register a session model (a :class:`SessionModel` or a
        registry spec string) under ``name``; warms its buckets."""
        with self._lock:
            # fail BEFORE the expensive build (bucket warmup compiles,
            # snapshotter thread) — a duplicate name is a caller error,
            # not worth seconds of work and a thread to tear down
            if name in self._managers:
                raise ServingError(
                    f"session model {name!r} already registered")
        if isinstance(model, str):
            model = build_session_model(model)
        kw.setdefault("snapshot_dir", self.snapshot_dir)
        kw.setdefault("buckets", self._buckets)
        manager = SessionManager(name, model, metrics=self.metrics,
                                 admission=self.admission, **kw)
        with self._lock:
            if name in self._managers:
                # raced another add: full teardown (decode loop AND
                # snapshotter), then the duplicate error
                manager.drain()
                raise ServingError(
                    f"session model {name!r} already registered")
            self._managers[name] = manager
        return manager

    def get(self, name):
        with self._lock:
            m = self._managers.get(name)
        if m is None:
            raise ModelNotFound(
                f"session model {name!r} is not registered")
        return m

    def names(self):
        with self._lock:
            return sorted(self._managers)

    def describe(self):
        with self._lock:
            managers = dict(self._managers)
        return {name: m.describe() for name, m in managers.items()}

    def stats(self):
        with self._lock:
            managers = dict(self._managers)
        return {name: m.stats() for name, m in managers.items()}

    def stream_hists(self):
        with self._lock:
            managers = dict(self._managers)
        return {name: m.stream_ms for name, m in managers.items()}

    def compile_counts(self):
        with self._lock:
            managers = dict(self._managers)
        return {name: m.model.compile_count
                for name, m in managers.items()}

    def queue_depths(self):
        with self._lock:
            managers = dict(self._managers)
        return {name: m.batcher.depth for name, m in managers.items()}

    def active_sessions(self):
        """Total live sessions across every manager — the signal the
        autoscaler's shrink victim-selection reads (a replica holding
        sessions is never preferred over a session-free one)."""
        with self._lock:
            managers = list(self._managers.values())
        total = 0
        for m in managers:
            with m._lock:
                total += len(m._sessions)
        return total

    def active_streams(self):
        """Streams currently riding any decode loop — a shrink only
        closes a replica once this reaches zero (or the drain budget
        expires): never mid-stream."""
        with self._lock:
            managers = list(self._managers.values())
        return sum(m.batcher.active_streams for m in managers)

    def drain_all(self, timeout=30.0):
        with self._lock:
            managers = list(self._managers.values())
        for m in managers:
            m.drain(timeout)
