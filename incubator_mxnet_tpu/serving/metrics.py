"""Serving observability: Prometheus-text counters + latency quantiles.

Pure stdlib — no prometheus_client.  One :class:`ServingMetrics`
instance is shared by the repository, batcher, admission layer and HTTP
front end; ``render()`` is the ``GET /metrics`` body and ``snapshot()``
the dict the profiler folds into its dumps (alongside ``bulk_stats``)
and the serving bench emits as JSON.

The load-bearing counter is ``mxnet_serving_compile_total``: the sum of
every loaded predictor's jit-cache size.  After warmup it must
flatline — growth under steady traffic means a request paid a cold XLA
compile, which on TPU is the difference between microseconds and
seconds.
"""
from __future__ import annotations

import threading
import time

from .. import trace
from ..locks import named_lock

__all__ = ["ServingMetrics", "FleetMetrics", "Histogram",
           "SlowExemplars"]


def _esc(label_value):
    """Prometheus label-value escaping (exposition format 0.0.4):
    one unescaped quote/backslash/newline in a model name would
    invalidate the whole /metrics page for every model."""
    return (str(label_value).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))

# defaults chosen for ms-scale serving latencies: sub-ms through 10s
_LATENCY_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0, 5000.0, 10000.0)
_RESERVOIR = 2048   # ring buffer per histogram for quantile estimates


class Histogram:
    """Fixed-bucket histogram + ring-buffer quantiles (p50/p95/p99).

    Prometheus histograms are cumulative-bucket counters; quantiles are
    computed host-side from the last ``_RESERVOIR`` observations, which
    is the summary-style view the bench and profiler dumps want."""

    def __init__(self, buckets=_LATENCY_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # +Inf tail
        self.total = 0
        self.sum = 0.0
        self._ring = [0.0] * _RESERVOIR
        self._lock = named_lock("metrics.histogram")

    def observe(self, value):
        value = float(value)
        with self._lock:
            i = 0
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self._ring[self.total % _RESERVOIR] = value
            self.total += 1
            self.sum += value

    def quantile(self, q):
        with self._lock:
            n = min(self.total, _RESERVOIR)
            if n == 0:
                return 0.0
            data = sorted(self._ring[:n])
        idx = min(n - 1, max(0, int(q * n)))
        return data[idx]

    def snapshot(self):
        with self._lock:
            total, s = self.total, self.sum
        return {"count": total, "sum": round(s, 3),
                "p50": round(self.quantile(0.50), 3),
                "p95": round(self.quantile(0.95), 3),
                "p99": round(self.quantile(0.99), 3)}

    def prom_lines(self, name, labels=""):
        lab = f"{{{labels}}}" if labels else ""
        out = []
        cum = 0
        with self._lock:
            counts, total, s = list(self.counts), self.total, self.sum
        for edge, c in zip(self.buckets, counts):
            cum += c
            sep = "," if labels else ""
            out.append(f'{name}_bucket{{{labels}{sep}le="{edge:g}"}} {cum}')
        sep = "," if labels else ""
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {total}')
        out.append(f"{name}_sum{lab} {s:.6f}")
        out.append(f"{name}_count{lab} {total}")
        return out


class SlowExemplars:
    """Trace-id exemplars for a latency histogram: the K slowest
    requests per observation window (``MXNET_TRACE_SLOW_K``).

    Histograms tell you THAT p99 spiked; an exemplar names a concrete
    trace id to pull from ``/v1/trace`` and see WHERE the time went.
    Windowing (default 512 observations) keeps the set current — a
    one-off stall from an hour ago ages out instead of squatting on
    the top-K forever.  The previous window is kept so a scrape right
    after rollover still sees exemplars."""

    __slots__ = ("_k", "_window", "_cur", "_prev", "_count", "_lock")

    def __init__(self, k=None, window=512):
        self._k = k
        self._window = int(window)
        self._cur: list = []     # [(ms, trace_id)] sorted desc
        self._prev: list = []
        self._count = 0
        self._lock = named_lock("metrics.slowk")

    def note(self, ms, trace_id):
        """Record one traced observation (untraced requests never get
        here — the caller gates on trace_id)."""
        if trace_id is None:
            return
        k = self._k if self._k is not None else trace.slow_k()
        if k <= 0:
            return
        with self._lock:
            self._count += 1
            if self._count % self._window == 0:
                self._prev, self._cur = self._cur, []
            cur = self._cur
            cur.append((float(ms), str(trace_id)))
            cur.sort(key=lambda t: -t[0])
            del cur[k:]

    def exemplars(self):
        """Top-K ``[{"ms", "trace_id"}]`` over the current + previous
        window, slowest first."""
        k = self._k if self._k is not None else trace.slow_k()
        with self._lock:
            merged = sorted(self._cur + self._prev,
                            key=lambda t: -t[0])[:max(0, k)]
        return [{"ms": round(ms, 3), "trace_id": tid}
                for ms, tid in merged]


class _ModelMetrics:
    __slots__ = ("requests", "errors", "batches", "batch_hist",
                 "e2e_ms", "compute_ms", "queue_ms", "padded_rows",
                 "cancelled", "t_last_request", "slow")

    def __init__(self):
        self.requests = {}       # {http-code: count}
        self.errors = 0
        self.batches = 0
        self.padded_rows = 0
        self.cancelled = 0
        # monotonic stamp of the last request (None until one lands):
        # the idle-seconds gauge the autoscaler's scale-to-zero /
        # idle-unload decision reads
        self.t_last_request = None
        self.batch_hist = Histogram(buckets=(1, 2, 4, 8, 16, 32, 64, 128))
        self.e2e_ms = Histogram()
        self.compute_ms = Histogram()
        self.queue_ms = Histogram()
        self.slow = SlowExemplars()   # K slowest traced requests


class ServingMetrics:
    """Process-wide serving counters, shared across models."""

    def __init__(self):
        self._models: dict[str, _ModelMetrics] = {}
        self._lock = named_lock("metrics.serving")
        self._started = time.monotonic()
        # callbacks the repository installs: () -> int / dict
        self._compile_count_fn = None
        self._queue_depth_fn = None
        self._memory_fn = None
        # cold-start observability (ROADMAP item 2): per-model load →
        # ready duration, process-start → ready, and AOT-executable
        # load outcomes, recorded by ModelRepository._build_entry
        self._cold_start: dict[str, dict] = {}
        # stateful sessions (SessionHost callbacks): per-model gauges
        # + the per-session-model compile counts folded into the
        # compile_total flatline invariant
        self._session_stats_fn = None
        self._session_hists_fn = None
        self._session_compile_fn = None

    def attach_repository(self, repository):
        """Wire gauges that live in the repository (compile counts per
        predictor, live queue depths per batcher, export-time memory
        plans per model)."""
        self._compile_count_fn = repository.compile_counts
        self._queue_depth_fn = repository.queue_depths
        self._memory_fn = getattr(repository, "memory_summaries", None)

    def attach_sessions(self, host):
        """Wire the session-host gauges (active sessions, steps,
        snapshots, stream latency) — and fold the session models'
        decode-step compile counts into ``mxnet_serving_compile_total``
        so the flatline-after-warmup invariant covers continuous
        batching: a session join/leave that cost an XLA compile moves
        the same counter a cold predict would."""
        self._session_stats_fn = host.stats
        self._session_hists_fn = host.stream_hists
        self._session_compile_fn = host.compile_counts

    def _model(self, name):
        with self._lock:
            m = self._models.get(name)
            if m is None:
                m = self._models[name] = _ModelMetrics()
            return m

    # -- recording hooks ----------------------------------------------

    def record_request(self, model, code, e2e_ms=None, compute_ms=None,
                       queue_ms=None, trace_id=None):
        m = self._model(model)
        with self._lock:
            m.requests[code] = m.requests.get(code, 0) + 1
            m.t_last_request = time.monotonic()
            if code >= 400:
                m.errors += 1
        if e2e_ms is not None:
            m.e2e_ms.observe(e2e_ms)
            if trace_id is not None:
                # exemplar: the histogram bucket gets a concrete trace
                # to name when someone asks "which request was that?"
                m.slow.note(e2e_ms, trace_id)
        if compute_ms is not None:
            m.compute_ms.observe(compute_ms)
        if queue_ms is not None:
            m.queue_ms.observe(queue_ms)

    def record_batch(self, model, batch_size, padded_to):
        m = self._model(model)
        with self._lock:
            m.batches += 1
            m.padded_rows += max(0, padded_to - batch_size)
        m.batch_hist.observe(batch_size)

    def record_cancel(self, model):
        """One request/stream withdrawn before (or between) device
        steps — client disconnects and lost hedge races land here."""
        m = self._model(model)
        with self._lock:
            m.cancelled += 1

    def record_cold_start(self, model, cold_start_ms, aot_loads=0,
                          aot_load_failures=0, compile_count=0):
        """One model version reached ready: how long load + warmup
        took, when after process start it happened, and whether the
        AOT executables carried it (``compile_count`` 0 with nonzero
        ``aot_loads`` = cold start was deserialization, not
        compilation)."""
        from .. import executor_cache as _xc
        with self._lock:
            prev = self._cold_start.get(model, {})
            self._cold_start[model] = {
                # gauges: the LIVE version's load cost
                "cold_start_ms": round(float(cold_start_ms), 3),
                "time_to_ready_ms": _xc.process_uptime_ms(),
                "compile_count_at_ready": int(compile_count),
                # counters: monotonic across reloads — a v2 exported
                # without AOT must not make the Prometheus series drop
                # (a decrease reads as a counter reset and fabricates
                # rate() deltas)
                "aot_loads": prev.get("aot_loads", 0) + int(aot_loads),
                "aot_load_failures": (prev.get("aot_load_failures", 0)
                                      + int(aot_load_failures)),
            }

    # -- exposition ---------------------------------------------------

    def compile_count(self):
        total = 0
        if self._compile_count_fn is not None:
            total += sum(self._compile_count_fn().values())
        if self._session_compile_fn is not None:
            total += sum(self._session_compile_fn().values())
        return total

    def idle_seconds(self, model=None):
        """Seconds since the model's last request — the autoscaler's
        idle-unload input signal.  A model that has never seen a
        request reports its full metrics-instance age (idle since
        "forever" as far as scale-to-zero is concerned).  With
        ``model=None`` returns the ``{name: idle_s}`` dict."""
        now = time.monotonic()
        with self._lock:
            if model is not None:
                m = self._models.get(model)
                last = (m.t_last_request if m is not None else None)
                return now - (last if last is not None
                              else self._started)
            return {name: now - (m.t_last_request
                                 if m.t_last_request is not None
                                 else self._started)
                    for name, m in self._models.items()}

    def last_request_uptime_s(self, model):
        """Monotonic stamp of the model's last request, expressed as
        seconds after this metrics instance started (``None`` until a
        request lands).  Monotonic by design — wall-clock timestamps
        are banned repo-wide (mxlint MX-TIME001); operators correlate
        via ``mxnet_serving_uptime_seconds`` on the same scrape."""
        with self._lock:
            m = self._models.get(model)
            if m is None or m.t_last_request is None:
                return None
            return m.t_last_request - self._started

    def service_ms_estimate(self, model):
        """Recent p50 end-to-end latency for ``model`` (None until
        observed) — the live term the derived ``Retry-After`` uses."""
        with self._lock:
            m = self._models.get(model)
        if m is None or m.e2e_ms.total == 0:
            return None
        return m.e2e_ms.quantile(0.5)

    def render(self):
        """Prometheus text exposition format (version 0.0.4)."""
        L = []
        L.append("# HELP mxnet_serving_uptime_seconds Server uptime.")
        L.append("# TYPE mxnet_serving_uptime_seconds gauge")
        L.append(f"mxnet_serving_uptime_seconds "
                 f"{time.monotonic() - self._started:.3f}")
        compiles = dict(self._compile_count_fn()
                        if self._compile_count_fn else {})
        if self._session_compile_fn is not None:
            for model, n in self._session_compile_fn().items():
                compiles[model] = compiles.get(model, 0) + n
        L.append("# HELP mxnet_serving_compile_total Distinct XLA "
                 "executables per model (must flatline after warmup).")
        L.append("# TYPE mxnet_serving_compile_total counter")
        for model, n in sorted(compiles.items()):
            L.append(f'mxnet_serving_compile_total'
                     f'{{model="{_esc(model)}"}} {n}')
        with self._lock:
            cold = {k: dict(v) for k, v in self._cold_start.items()}
        L.append("# HELP mxnet_serving_cold_start_ms Load + warmup "
                 "duration of the live model version.")
        L.append("# TYPE mxnet_serving_cold_start_ms gauge")
        for model, c in sorted(cold.items()):
            L.append(f'mxnet_serving_cold_start_ms'
                     f'{{model="{_esc(model)}"}} {c["cold_start_ms"]}')
        L.append("# HELP mxnet_serving_time_to_ready_ms Process start "
                 "to model ready.")
        L.append("# TYPE mxnet_serving_time_to_ready_ms gauge")
        for model, c in sorted(cold.items()):
            L.append(f'mxnet_serving_time_to_ready_ms'
                     f'{{model="{_esc(model)}"}} {c["time_to_ready_ms"]}')
        L.append("# HELP mxnet_serving_aot_loads_total AOT executables "
                 "deserialized per model (cache hits that skipped XLA).")
        L.append("# TYPE mxnet_serving_aot_loads_total counter")
        for model, c in sorted(cold.items()):
            L.append(f'mxnet_serving_aot_loads_total'
                     f'{{model="{_esc(model)}"}} {c["aot_loads"]}')
        L.append("# HELP mxnet_serving_aot_load_failures_total AOT "
                 "blobs refused (compat mismatch/corruption) per model "
                 "— each one recompiled instead.")
        L.append("# TYPE mxnet_serving_aot_load_failures_total counter")
        for model, c in sorted(cold.items()):
            L.append(f'mxnet_serving_aot_load_failures_total'
                     f'{{model="{_esc(model)}"}} '
                     f'{c["aot_load_failures"]}')
        depths = (self._queue_depth_fn() if self._queue_depth_fn else {})
        L.append("# HELP mxnet_serving_queue_depth In-flight + queued "
                 "requests per model.")
        L.append("# TYPE mxnet_serving_queue_depth gauge")
        for model, n in sorted(depths.items()):
            L.append(f'mxnet_serving_queue_depth'
                     f'{{model="{_esc(model)}"}} {n}')
        mem = (self._memory_fn() if self._memory_fn else {})
        L.append("# HELP mxnet_serving_model_peak_hbm_bytes Static "
                 "peak-HBM estimate of the exported forward (memlint).")
        L.append("# TYPE mxnet_serving_model_peak_hbm_bytes gauge")
        for model, m in sorted(mem.items()):
            if m.get("peak_hbm_bytes") is not None:
                L.append(f'mxnet_serving_model_peak_hbm_bytes'
                         f'{{model="{_esc(model)}"}} '
                         f'{m["peak_hbm_bytes"]}')
        L.append("# HELP mxnet_serving_model_donated_bytes_reclaimed "
                 "Input bytes XLA reuses for outputs via buffer "
                 "donation (memlint plan).")
        L.append("# TYPE mxnet_serving_model_donated_bytes_reclaimed "
                 "gauge")
        for model, m in sorted(mem.items()):
            if m.get("donated_bytes_reclaimed") is not None:
                L.append(f'mxnet_serving_model_donated_bytes_reclaimed'
                         f'{{model="{_esc(model)}"}} '
                         f'{m["donated_bytes_reclaimed"]}')
        with self._lock:
            models = dict(self._models)
        L.append("# HELP mxnet_serving_requests_total Requests by "
                 "model and HTTP code.")
        L.append("# TYPE mxnet_serving_requests_total counter")
        for name, m in sorted(models.items()):
            with self._lock:
                codes = dict(m.requests)
            for code, n in sorted(codes.items()):
                L.append(f'mxnet_serving_requests_total'
                         f'{{model="{_esc(name)}",code="{code}"}} {n}')
        L.append("# HELP mxnet_serving_errors_total 4xx/5xx responses.")
        L.append("# TYPE mxnet_serving_errors_total counter")
        for name, m in sorted(models.items()):
            L.append(f'mxnet_serving_errors_total'
                     f'{{model="{_esc(name)}"}} {m.errors}')
        L.append("# HELP mxnet_serving_batches_total Coalesced batches "
                 "executed.")
        L.append("# TYPE mxnet_serving_batches_total counter")
        for name, m in sorted(models.items()):
            L.append(f'mxnet_serving_batches_total'
                     f'{{model="{_esc(name)}"}} {m.batches}')
        L.append("# HELP mxnet_serving_padded_rows_total Wasted rows "
                 "from bucket padding.")
        L.append("# TYPE mxnet_serving_padded_rows_total counter")
        for name, m in sorted(models.items()):
            L.append(f'mxnet_serving_padded_rows_total'
                     f'{{model="{_esc(name)}"}} {m.padded_rows}')
        L.append("# HELP mxnet_serving_cancelled_total Requests/"
                 "streams withdrawn before execution (client "
                 "disconnects, lost hedge races).")
        L.append("# TYPE mxnet_serving_cancelled_total counter")
        for name, m in sorted(models.items()):
            L.append(f'mxnet_serving_cancelled_total'
                     f'{{model="{_esc(name)}"}} {m.cancelled}')
        L.append("# HELP mxnet_serving_model_idle_seconds Seconds "
                 "since the model's last request (the autoscaler's "
                 "idle-unload signal).")
        L.append("# TYPE mxnet_serving_model_idle_seconds gauge")
        idle = self.idle_seconds()
        for name in sorted(models):
            L.append(f'mxnet_serving_model_idle_seconds'
                     f'{{model="{_esc(name)}"}} {idle[name]:.3f}')
        L.append("# HELP mxnet_serving_model_last_request_uptime_"
                 "seconds Last request's monotonic stamp as seconds "
                 "after metrics start (-1 until a request lands; "
                 "correlate with mxnet_serving_uptime_seconds).")
        L.append("# TYPE mxnet_serving_model_last_request_uptime_"
                 "seconds gauge")
        for name in sorted(models):
            last = self.last_request_uptime_s(name)
            L.append(f'mxnet_serving_model_last_request_uptime_seconds'
                     f'{{model="{_esc(name)}"}} '
                     f'{-1 if last is None else round(last, 3)}')
        sess = (self._session_stats_fn() if self._session_stats_fn
                else {})
        for metric, key, kind, help_ in (
                ("mxnet_serving_session_active", "active_sessions",
                 "gauge", "Live sessions per session model."),
                ("mxnet_serving_session_steps_total", "steps_total",
                 "counter", "Decode steps executed."),
                ("mxnet_serving_session_snapshots_total",
                 "snapshots_total", "counter",
                 "Carry snapshots written (CRC'd shard format)."),
                ("mxnet_serving_session_snapshot_failures_total",
                 "snapshot_failures_total", "counter",
                 "Snapshot attempts that failed (stream unaffected)."),
                ("mxnet_serving_session_evictions_total",
                 "evictions_total", "counter",
                 "Sessions evicted (idle TTL / session cap)."),
                ("mxnet_serving_session_restored_total",
                 "restored_total", "counter",
                 "Sessions adopted from a snapshot (migrations in)."),
                ("mxnet_serving_session_snapshot_age_s",
                 "snapshot_age_s", "gauge",
                 "Oldest live session's seconds since last snapshot "
                 "(the migration re-base window).")):
            L.append(f"# HELP {metric} {help_}")
            L.append(f"# TYPE {metric} {kind}")
            for name, st in sorted(sess.items()):
                L.append(f'{metric}{{model="{_esc(name)}"}} '
                         f'{st[key]}')
        hists = (self._session_hists_fn() if self._session_hists_fn
                 else {})
        L.append("# HELP mxnet_serving_session_stream_ms Per-chunk "
                 "decode-step latency of session streams.")
        L.append("# TYPE mxnet_serving_session_stream_ms histogram")
        for name, h in sorted(hists.items()):
            L.extend(h.prom_lines("mxnet_serving_session_stream_ms",
                                  f'model="{_esc(name)}"'))
        L.append("# HELP mxnet_serving_batch_size Coalesced batch sizes.")
        L.append("# TYPE mxnet_serving_batch_size histogram")
        for name, m in sorted(models.items()):
            L.extend(m.batch_hist.prom_lines("mxnet_serving_batch_size",
                                             f'model="{_esc(name)}"'))
        for metric, attr, help_ in (
                ("mxnet_serving_latency_ms", "e2e_ms",
                 "End-to-end request latency."),
                ("mxnet_serving_compute_ms", "compute_ms",
                 "Device compute time per request."),
                ("mxnet_serving_queue_ms", "queue_ms",
                 "Queue wait per request.")):
            L.append(f"# HELP {metric} {help_}")
            L.append(f"# TYPE {metric} histogram")
            for name, m in sorted(models.items()):
                L.extend(getattr(m, attr).prom_lines(
                    metric, f'model="{_esc(name)}"'))
        # slow-request exemplars as comments (docs/observability.md):
        # the trace ids of the K slowest traced requests per window —
        # text-format-legal ('#' lines), so a plain scraper ignores
        # them while a human (or traceview) reads the ids right off
        # the /metrics page
        for name, m in sorted(models.items()):
            for ex in m.slow.exemplars():
                L.append(f'# exemplar mxnet_serving_latency_ms'
                         f'{{model="{_esc(name)}"}} '
                         f'trace_id={ex["trace_id"]} ms={ex["ms"]}')
        return "\n".join(L) + "\n"

    def snapshot(self):
        """Flat dict view: profiler dumps + serving bench JSON."""
        with self._lock:
            models = dict(self._models)
        out = {"compile_total": self.compile_count()}
        if self._queue_depth_fn is not None:
            out["queue_depth"] = sum(self._queue_depth_fn().values())
        with self._lock:
            for name, c in self._cold_start.items():
                out[f"{name}.cold_start_ms"] = c["cold_start_ms"]
                out[f"{name}.time_to_ready_ms"] = c["time_to_ready_ms"]
                out[f"{name}.aot_loads"] = c["aot_loads"]
                out[f"{name}.aot_load_failures"] = c["aot_load_failures"]
        if self._memory_fn is not None:
            for name, m in self._memory_fn().items():
                if m.get("peak_hbm_bytes") is not None:
                    out[f"{name}.peak_hbm_bytes"] = m["peak_hbm_bytes"]
                if m.get("donated_bytes_reclaimed") is not None:
                    out[f"{name}.donated_bytes_reclaimed"] = \
                        m["donated_bytes_reclaimed"]
        if self._session_stats_fn is not None:
            for name, st in self._session_stats_fn().items():
                for k, v in st.items():
                    out[f"{name}.session.{k}"] = v
        for name, m in models.items():
            with self._lock:
                reqs = sum(m.requests.values())
                errs, batches = m.errors, m.batches
                padded, cancelled = m.padded_rows, m.cancelled
            out[f"{name}.requests"] = reqs
            out[f"{name}.idle_s"] = round(self.idle_seconds(name), 3)
            out[f"{name}.errors"] = errs
            out[f"{name}.batches"] = batches
            out[f"{name}.padded_rows"] = padded
            out[f"{name}.cancelled"] = cancelled
            out[f"{name}.batch_size"] = m.batch_hist.snapshot()
            out[f"{name}.e2e_ms"] = m.e2e_ms.snapshot()
            out[f"{name}.compute_ms"] = m.compute_ms.snapshot()
            out[f"{name}.queue_ms"] = m.queue_ms.snapshot()
            slow = m.slow.exemplars()
            if slow:
                out[f"{name}.slow_traces"] = slow
        return out

    def register_with_profiler(self):
        """Fold the serving counters into ``profiler.dumps()`` output
        alongside ``bulk_stats``."""
        from .. import profiler
        profiler.register_stats_provider("serving", self.snapshot)

    def unregister_from_profiler(self):
        """Detach at server shutdown: a dead server must not keep its
        repository (predictors, weights) alive through the profiler's
        provider registry nor report stale counters in later dumps."""
        from .. import profiler
        profiler.unregister_stats_provider("serving", self.snapshot)


class _RouteModel:
    """Per-model router-side counters (the autoscaler's load signal)."""

    __slots__ = ("requests", "e2e_ms", "t_last", "inflight", "slow")

    def __init__(self):
        self.requests = {}       # {final-http-code: count}
        self.e2e_ms = Histogram()
        self.t_last = None       # monotonic stamp of last route
        self.inflight = 0
        self.slow = SlowExemplars()   # K slowest traced routes


class FleetMetrics:
    """Fleet-level observability: the router + replica-lifecycle view.

    Per-replica serving counters (batches, compile counts, latency
    histograms) live on each replica's own :class:`ServingMetrics`;
    this class carries what only the fleet layer can see — replica
    states and inflight load, active-probe failures, failovers, and
    the hedging win rate.  Rendered into the router's ``/metrics``
    page and folded into ``profiler.dumps()`` as ``serving_fleet``."""

    def __init__(self):
        self._lock = named_lock("metrics.fleet")
        self._started = time.monotonic()
        self._codes: dict = {}            # {http-code: count}
        self._probe_failures: dict = {}   # {replica-id: count}
        self.failovers = 0
        self.hedges_launched = 0
        self.hedges_won = 0
        self.migrations = 0               # session carries re-homed
        self.session_losses = 0           # typed SessionLostError out
        self.route_cancels = 0            # client gone mid-route
        self.route_ms = Histogram()
        self.slow = SlowExemplars()       # fleet-level slow exemplars
        # per-model router view: the autoscaler's input signal (queue
        # depth rides on replica healthz; p99 / inflight / idle live
        # here, where every routed request passes)
        self._by_model: dict = {}         # {model: _RouteModel}
        self._fleet_states_fn = None      # () -> {rid: state-dict}
        self._session_count_fn = None     # () -> live affinity entries
        self._autoscale_fn = None         # () -> autoscaler.describe()

    def attach_fleet(self, fleet):
        """Wire the live replica-state gauge callback."""
        self._fleet_states_fn = fleet.states

    def attach_session_count(self, fn):
        """Wire the router's session-affinity gauge (sessions the
        fleet currently tracks, wherever their carry lives)."""
        self._session_count_fn = fn

    def attach_autoscaler(self, fn):
        """Wire the autoscaler's describe callback so desired-vs-
        actual replica counts and scale-decision counters render on
        the router's ``/metrics`` page."""
        self._autoscale_fn = fn

    def _route_model(self, model):
        with self._lock:
            m = self._by_model.get(model)
            if m is None:
                m = self._by_model[model] = _RouteModel()
            return m

    # -- recording hooks ----------------------------------------------

    def record_route(self, code, ms=None, model=None, trace_id=None):
        with self._lock:
            self._codes[code] = self._codes.get(code, 0) + 1
        if ms is not None:
            self.route_ms.observe(ms)
            if trace_id is not None:
                self.slow.note(ms, trace_id)
        if model is not None:
            m = self._route_model(model)
            with self._lock:
                m.requests[code] = m.requests.get(code, 0) + 1
                m.t_last = time.monotonic()
            if ms is not None:
                m.e2e_ms.observe(ms)
                if trace_id is not None:
                    m.slow.note(ms, trace_id)

    def note_model_inflight(self, model, delta):
        """Routed-requests-in-flight gauge per model (bumped around
        each route; part of the autoscaler's load signal)."""
        m = self._route_model(model)
        with self._lock:
            m.inflight = max(0, m.inflight + int(delta))

    def model_idle_s(self, model):
        """Seconds since the last routed request for ``model``; a
        model never routed reports this instance's full age."""
        with self._lock:
            m = self._by_model.get(model)
            last = m.t_last if m is not None else None
            return time.monotonic() - (last if last is not None
                                       else self._started)

    def model_stats(self):
        """{model: {requests, dropped, p50_ms, p99_ms, inflight,
        idle_s}} — the router-side half of the autoscaler's signal."""
        now = time.monotonic()
        with self._lock:
            items = list(self._by_model.items())
        out = {}
        for name, m in items:
            with self._lock:
                reqs = dict(m.requests)
                inflight = m.inflight
                last = m.t_last
            out[name] = {
                "requests": sum(reqs.values()),
                "dropped": sum(n for c, n in reqs.items()
                               if c in (429, 503)),
                "p50_ms": m.e2e_ms.quantile(0.50),
                "p99_ms": m.e2e_ms.quantile(0.99),
                "inflight": inflight,
                "idle_s": round(now - (last if last is not None
                                       else self._started), 3),
            }
        return out

    def record_failover(self):
        with self._lock:
            self.failovers += 1

    def record_hedge(self, won=False):
        with self._lock:
            if won:
                self.hedges_won += 1
            else:
                self.hedges_launched += 1

    def record_probe_failure(self, replica_id):
        with self._lock:
            self._probe_failures[replica_id] = (
                self._probe_failures.get(replica_id, 0) + 1)

    def record_migration(self):
        """One session adopted onto a new replica from its snapshot."""
        with self._lock:
            self.migrations += 1

    def record_session_loss(self):
        """One session surfaced typed ``SessionLostError`` — the
        failover contract's explicit failure arm, never a hang."""
        with self._lock:
            self.session_losses += 1

    def record_route_cancel(self):
        """Client disconnected while its request was still between
        hops — abandoned before more device time was spent."""
        with self._lock:
            self.route_cancels += 1

    # -- exposition ---------------------------------------------------

    def _replica_states(self):
        return self._fleet_states_fn() if self._fleet_states_fn else {}

    def render(self):
        """Prometheus text exposition for the router's ``/metrics``."""
        L = []
        states = self._replica_states()
        L.append("# HELP mxnet_serving_fleet_replica_state Replica "
                 "lifecycle state (1 for the current state).")
        L.append("# TYPE mxnet_serving_fleet_replica_state gauge")
        for rid, st in sorted(states.items()):
            L.append(f'mxnet_serving_fleet_replica_state'
                     f'{{replica="{_esc(rid)}",'
                     f'state="{_esc(st["state"])}"}} 1')
        L.append("# HELP mxnet_serving_fleet_replica_inflight Routed "
                 "requests currently on each replica.")
        L.append("# TYPE mxnet_serving_fleet_replica_inflight gauge")
        for rid, st in sorted(states.items()):
            L.append(f'mxnet_serving_fleet_replica_inflight'
                     f'{{replica="{_esc(rid)}"}} {st["inflight"]}')
        L.append("# HELP mxnet_serving_fleet_replica_healthy Probe "
                 "verdict: 1 routable, 0 quarantined.")
        L.append("# TYPE mxnet_serving_fleet_replica_healthy gauge")
        for rid, st in sorted(states.items()):
            L.append(f'mxnet_serving_fleet_replica_healthy'
                     f'{{replica="{_esc(rid)}"}} '
                     f'{1 if st["healthy"] else 0}')
        ready = sum(1 for st in states.values()
                    if st["state"] == "ready" and st["healthy"])
        L.append("# HELP mxnet_serving_fleet_ready_replicas Replicas "
                 "ready and healthy (routable).")
        L.append("# TYPE mxnet_serving_fleet_ready_replicas gauge")
        L.append(f"mxnet_serving_fleet_ready_replicas {ready}")
        with self._lock:
            codes = dict(self._codes)
            probe_failures = dict(self._probe_failures)
            failovers = self.failovers
            launched, won = self.hedges_launched, self.hedges_won
            migrations, losses = self.migrations, self.session_losses
            route_cancels = self.route_cancels
        L.append("# HELP mxnet_serving_fleet_sessions Sessions the "
                 "router currently tracks affinity for.")
        L.append("# TYPE mxnet_serving_fleet_sessions gauge")
        L.append(f"mxnet_serving_fleet_sessions "
                 f"{self._session_count_fn() if self._session_count_fn else 0}")
        L.append("# HELP mxnet_serving_fleet_session_migrations_total "
                 "Sessions re-homed from a snapshot after replica "
                 "death or drain.")
        L.append("# TYPE mxnet_serving_fleet_session_migrations_total "
                 "counter")
        L.append(f"mxnet_serving_fleet_session_migrations_total "
                 f"{migrations}")
        L.append("# HELP mxnet_serving_fleet_session_losses_total "
                 "Sessions that surfaced typed SessionLostError (no "
                 "recoverable snapshot).")
        L.append("# TYPE mxnet_serving_fleet_session_losses_total "
                 "counter")
        L.append(f"mxnet_serving_fleet_session_losses_total {losses}")
        L.append("# HELP mxnet_serving_fleet_route_cancels_total "
                 "Routed requests abandoned between hops because the "
                 "client disconnected.")
        L.append("# TYPE mxnet_serving_fleet_route_cancels_total "
                 "counter")
        L.append(f"mxnet_serving_fleet_route_cancels_total "
                 f"{route_cancels}")
        L.append("# HELP mxnet_serving_fleet_requests_total Routed "
                 "requests by final HTTP code.")
        L.append("# TYPE mxnet_serving_fleet_requests_total counter")
        for code, n in sorted(codes.items()):
            L.append(f'mxnet_serving_fleet_requests_total'
                     f'{{code="{code}"}} {n}')
        with self._lock:
            by_model = dict(self._by_model)
        L.append("# HELP mxnet_serving_fleet_model_requests_total "
                 "Routed requests by model and final HTTP code.")
        L.append("# TYPE mxnet_serving_fleet_model_requests_total "
                 "counter")
        for name, m in sorted(by_model.items()):
            with self._lock:
                mcodes = dict(m.requests)
            for code, n in sorted(mcodes.items()):
                L.append(f'mxnet_serving_fleet_model_requests_total'
                         f'{{model="{_esc(name)}",code="{code}"}} {n}')
        L.append("# HELP mxnet_serving_fleet_model_inflight Routed "
                 "requests currently in flight per model.")
        L.append("# TYPE mxnet_serving_fleet_model_inflight gauge")
        for name, m in sorted(by_model.items()):
            L.append(f'mxnet_serving_fleet_model_inflight'
                     f'{{model="{_esc(name)}"}} {m.inflight}')
        L.append("# HELP mxnet_serving_model_idle_seconds Seconds "
                 "since the model's last routed request (the "
                 "autoscaler's idle-unload signal).")
        L.append("# TYPE mxnet_serving_model_idle_seconds gauge")
        for name in sorted(by_model):
            L.append(f'mxnet_serving_model_idle_seconds'
                     f'{{model="{_esc(name)}"}} '
                     f'{self.model_idle_s(name):.3f}')
        scale = (self._autoscale_fn() if self._autoscale_fn else None)
        if scale is not None:
            L.append("# HELP mxnet_serving_autoscale_desired_replicas "
                     "Replica copies the control loop wants per model.")
            L.append("# TYPE mxnet_serving_autoscale_desired_replicas "
                     "gauge")
            for name, st in sorted(scale.get("models", {}).items()):
                L.append(f'mxnet_serving_autoscale_desired_replicas'
                         f'{{model="{_esc(name)}"}} {st["desired"]}')
            L.append("# HELP mxnet_serving_autoscale_actual_replicas "
                     "Replica copies currently serving per model.")
            L.append("# TYPE mxnet_serving_autoscale_actual_replicas "
                     "gauge")
            for name, st in sorted(scale.get("models", {}).items()):
                L.append(f'mxnet_serving_autoscale_actual_replicas'
                         f'{{model="{_esc(name)}"}} {st["actual"]}')
            L.append("# HELP mxnet_serving_autoscale_decisions_total "
                     "Scale decisions applied, by action.")
            L.append("# TYPE mxnet_serving_autoscale_decisions_total "
                     "counter")
            for action, n in sorted(
                    scale.get("decisions", {}).items()):
                L.append(f'mxnet_serving_autoscale_decisions_total'
                         f'{{action="{_esc(action)}"}} {n}')
            L.append("# HELP mxnet_serving_autoscale_evictions_total "
                     "Models evicted from a replica by the HBM "
                     "bin-packer (LRU), by model.")
            L.append("# TYPE mxnet_serving_autoscale_evictions_total "
                     "counter")
            for name, n in sorted(
                    scale.get("evictions", {}).items()):
                L.append(f'mxnet_serving_autoscale_evictions_total'
                         f'{{model="{_esc(name)}"}} {n}')
            L.append("# HELP mxnet_serving_autoscale_replica_seconds_"
                     "total Integrated live-replica time (the fleet-"
                     "economics number the autoscale bench gates).")
            L.append("# TYPE mxnet_serving_autoscale_replica_seconds_"
                     "total counter")
            L.append(f"mxnet_serving_autoscale_replica_seconds_total "
                     f"{scale.get('replica_seconds', 0.0):.3f}")
        L.append("# HELP mxnet_serving_fleet_failovers_total Request "
                 "hops retried on a different replica.")
        L.append("# TYPE mxnet_serving_fleet_failovers_total counter")
        L.append(f"mxnet_serving_fleet_failovers_total {failovers}")
        L.append("# HELP mxnet_serving_fleet_probe_failures_total "
                 "Active health-probe failures per replica.")
        L.append("# TYPE mxnet_serving_fleet_probe_failures_total "
                 "counter")
        for rid, n in sorted(probe_failures.items()):
            L.append(f'mxnet_serving_fleet_probe_failures_total'
                     f'{{replica="{_esc(rid)}"}} {n}')
        L.append("# HELP mxnet_serving_fleet_hedges_total Hedged "
                 "second requests launched / won the race.")
        L.append("# TYPE mxnet_serving_fleet_hedges_total counter")
        L.append(f'mxnet_serving_fleet_hedges_total'
                 f'{{event="launched"}} {launched}')
        L.append(f'mxnet_serving_fleet_hedges_total'
                 f'{{event="won"}} {won}')
        L.append("# HELP mxnet_serving_fleet_route_ms End-to-end "
                 "routed request latency (all hops + hedges).")
        L.append("# TYPE mxnet_serving_fleet_route_ms histogram")
        L.extend(self.route_ms.prom_lines("mxnet_serving_fleet_route_ms"))
        # slow-route exemplars: trace ids to feed tools/traceview.py
        # (fleet-wide, then per model) — comment lines, scraper-inert
        for ex in self.slow.exemplars():
            L.append(f'# exemplar mxnet_serving_fleet_route_ms '
                     f'trace_id={ex["trace_id"]} ms={ex["ms"]}')
        for name, m in sorted(by_model.items()):
            for ex in m.slow.exemplars():
                L.append(f'# exemplar mxnet_serving_fleet_route_ms'
                         f'{{model="{_esc(name)}"}} '
                         f'trace_id={ex["trace_id"]} ms={ex["ms"]}')
        return "\n".join(L) + "\n"

    def snapshot(self):
        """Flat dict view for profiler dumps and the fleet bench."""
        states = self._replica_states()
        with self._lock:
            out = {
                "replicas": {rid: dict(st)
                             for rid, st in sorted(states.items())},
                "ready": sum(1 for st in states.values()
                             if st["state"] == "ready"
                             and st["healthy"]),
                "requests": dict(self._codes),
                "failovers": self.failovers,
                "hedges_launched": self.hedges_launched,
                "hedges_won": self.hedges_won,
                "migrations": self.migrations,
                "session_losses": self.session_losses,
                "route_cancels": self.route_cancels,
                "sessions": (self._session_count_fn()
                             if self._session_count_fn else 0),
                "probe_failures": dict(self._probe_failures),
            }
        out["route_ms"] = self.route_ms.snapshot()
        out["models"] = self.model_stats()
        slow = self.slow.exemplars()
        if slow:
            out["slow_traces"] = slow
        if self._autoscale_fn is not None:
            out["autoscale"] = self._autoscale_fn()
        return out

    def register_with_profiler(self):
        from .. import profiler
        profiler.register_stats_provider("serving_fleet", self.snapshot)

    def unregister_from_profiler(self):
        """Detach at router shutdown — mirrors
        :meth:`ServingMetrics.unregister_from_profiler`: a dead fleet
        must not be kept alive by the provider registry."""
        from .. import profiler
        profiler.unregister_stats_provider("serving_fleet",
                                           self.snapshot)
