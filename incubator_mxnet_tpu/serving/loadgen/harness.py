"""Soak harness: a real subprocess fleet under seeded chaos, driven
by a compiled workload schedule, judged by the observability stack.

The harness stands up router subprocess(es) (two + ``--ha-dir`` for
router-kill scenarios) over thread- or process-backend replicas, then
replays a :class:`~.workload.Schedule` through the HTTP clients while
an :class:`IncidentScheduler` fires scripted incidents (SIGKILL a
replica at virtual *t*, SIGKILL a router at *t*; fault-point bursts
are pre-armed in the chaos spec's ``after=``/``n=`` counters and
gated post-hoc).  Verdicts come from three independent witnesses:

* :class:`SloMonitor` — per-class, per-virtual-minute latency
  conformance against the ``MXNET_SOAK_SLO_MS`` targets;
* :class:`StreamLedger` — zero lost streams, bitwise: every session's
  chunks placed at absolute step indices must cover ``0..N-1`` and
  equal an unbroken single-session reference run;
* ``tools/postmortem.py --gate`` — every injected incident must be
  reconstructable from the surviving flight rings.

Everything a failed soak needs to replay is in the report:
``(workload, seed, time_scale, chaos_spec)``.
"""
from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
from typing import NamedTuple

import numpy as onp

from ... import fault
from ...base import get_env
from ...locks import named_lock
from .clients import (PredictClient, SessionClient, StreamBroken,
                      percentile, scrape, SLO_HEADER)

__all__ = ["Incident", "IncidentScheduler", "SloMonitor",
           "StreamLedger", "SoakHarness", "parse_prometheus",
           "slo_targets", "metric_sum"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
POSTMORTEM = os.path.join(_REPO, "tools", "postmortem.py")

SESSION_SPEC = "toy_decoder:dim=8,max_len=64"
SESSION_DIM = 8


def slo_targets() -> dict:
    """Per-class latency targets (ms) from ``MXNET_SOAK_SLO_MS``
    (``class=ms`` entries, comma-joined)."""
    raw = get_env("MXNET_SOAK_SLO_MS",
                  "interactive=500,standard=2000,batch=10000")
    targets = {}
    for entry in filter(None, (e.strip() for e in raw.split(","))):
        k, sep, v = entry.partition("=")
        if not sep:
            raise ValueError(
                f"MXNET_SOAK_SLO_MS entry {entry!r}: want class=ms")
        targets[k.strip()] = float(v)
    return targets


# ---------------------------------------------------------------------------
# /metrics conformance reader
# ---------------------------------------------------------------------------

_LABELS_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')


def _split_series(tok: str):
    if "{" in tok:
        name, _, rest = tok.partition("{")
        return name, dict(_LABELS_RE.findall(rest))
    return tok, {}


def parse_prometheus(text: str) -> dict:
    """Parse a Prometheus text exposition (the router's ``/metrics``)
    into ``{"samples": [(name, labels, value)], "exemplars": [...]}``.
    Exemplar comments (``# exemplar name{labels} k=v ...``) are the
    slow-trace breadcrumbs the soak report surfaces."""
    out = {"samples": [], "exemplars": []}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            if line.startswith("# exemplar "):
                tok, _, rest = line[len("# exemplar "):].partition(" ")
                name, labels = _split_series(tok)
                fields = dict(kv.split("=", 1)
                              for kv in rest.split() if "=" in kv)
                out["exemplars"].append(
                    {"name": name, "labels": labels, "fields": fields})
            continue
        tok, _, val = line.rpartition(" ")
        try:
            value = float(val)
        except ValueError:
            continue
        name, labels = _split_series(tok)
        out["samples"].append((name, labels, value))
    return out


def metric_sum(parsed: dict, name: str, **labels) -> float:
    """Sum every sample of ``name`` whose labels include ``labels``."""
    return sum(v for n, lab, v in parsed["samples"]
               if n == name and all(lab.get(k) == want
                                    for k, want in labels.items()))


# ---------------------------------------------------------------------------
# SLO conformance
# ---------------------------------------------------------------------------

class SloMonitor:
    """Per-class latency observations binned by VIRTUAL minute.

    A minute violates its class when any request in it failed outright
    or its in-minute p99 exceeds the class target.  Latencies are real
    milliseconds (time compression squeezes arrival spacing, never the
    server's actual response time), binned by the virtual clock so a
    compressed 30-minute diurnal still reports 30 one-minute verdicts.
    """

    def __init__(self, targets: dict | None = None):
        self.targets = dict(slo_targets() if targets is None
                            else targets)
        self._obs = []
        self._lock = named_lock("loadgen.slo")

    def observe(self, t_virtual, slo, ms, ok=True):
        with self._lock:
            self._obs.append((int(t_virtual // 60.0), str(slo),
                              float(ms), bool(ok)))

    def report(self) -> dict:
        with self._lock:
            obs = list(self._obs)
        per: dict = {}
        for minute, slo, ms, ok in obs:
            d = per.setdefault(slo, {"lat": [], "minutes": {},
                                     "failures": 0})
            d["lat"].append(ms)
            m = d["minutes"].setdefault(minute,
                                        {"lat": [], "failures": 0})
            m["lat"].append(ms)
            if not ok:
                d["failures"] += 1
                m["failures"] += 1
        out = {}
        for slo, d in sorted(per.items()):
            target = self.targets.get(slo)
            violating = []
            for minute, m in sorted(d["minutes"].items()):
                p99 = percentile(m["lat"], 0.99)
                if m["failures"] or (target is not None
                                     and p99 > target):
                    violating.append(minute)
            out[slo] = {"requests": len(d["lat"]),
                        "failures": d["failures"],
                        "p50_ms": round(percentile(d["lat"], 0.5), 3),
                        "p99_ms": round(percentile(d["lat"], 0.99), 3),
                        "target_ms": target,
                        "violating_minutes": violating}
        return out


# ---------------------------------------------------------------------------
# zero lost streams, bitwise
# ---------------------------------------------------------------------------

def _freeze(row):
    return tuple(float(x)
                 for x in onp.asarray(row, dtype=onp.float64).ravel())


class StreamLedger:
    """Absolute-index chunk ledger: the zero-lost-streams witness.

    Clients record only COMPLETED stream calls, each as
    ``(base, chunks)`` with ``base = session_steps - steps`` — so
    after any number of migrations, re-bases and replays, the ledger
    holds every session's rows keyed by absolute step index.  A lost
    stream is then undeniable: a hole in ``0..N-1`` coverage, a
    bitwise divergence from the unbroken reference, or two deliveries
    of the same index that disagree.
    """

    def __init__(self):
        self._rows: dict = {}    # sid -> {step index: frozen row}
        self._meta: dict = {}    # sid -> {"steps": N, "value": v}
        self.conflicts: list = []
        self._lock = named_lock("loadgen.consistency")

    def expect(self, sid, steps, value):
        with self._lock:
            self._meta[sid] = {"steps": int(steps),
                               "value": float(value)}

    def meta(self) -> dict:
        with self._lock:
            return dict(self._meta)

    def record(self, sid, base, chunks):
        with self._lock:
            rows = self._rows.setdefault(sid, {})
            for j, chunk in enumerate(chunks):
                idx = int(base) + j
                row = _freeze(chunk)
                if idx in rows and rows[idx] != row:
                    self.conflicts.append(
                        {"sid": sid, "kind": "conflict",
                         "steps": [idx], "total": 1})
                rows[idx] = row

    def verify(self, references: dict) -> list:
        """``references`` maps sid -> full unbroken row list.  Returns
        the failure list (empty == zero lost streams)."""
        with self._lock:
            failures = list(self.conflicts)
            for sid, ref in sorted(references.items()):
                rows = self._rows.get(sid, {})
                want = [_freeze(r) for r in ref]
                missing = [i for i in range(len(want))
                           if i not in rows]
                if missing:
                    failures.append({"sid": sid, "kind": "missing",
                                     "steps": missing[:8],
                                     "total": len(missing)})
                    continue
                diverged = [i for i, w in enumerate(want)
                            if rows[i] != w]
                if diverged:
                    failures.append({"sid": sid, "kind": "diverged",
                                     "steps": diverged[:8],
                                     "total": len(diverged)})
                phantom = sorted(i for i in rows if i >= len(want))
                if phantom:
                    failures.append({"sid": sid, "kind": "phantom",
                                     "steps": phantom[:8],
                                     "total": len(phantom)})
        return failures


# ---------------------------------------------------------------------------
# scripted incidents in virtual time
# ---------------------------------------------------------------------------

class Incident(NamedTuple):
    """One scripted incident: fire ``kind`` at virtual second ``t``,
    then demand that ``gate`` (a ``postmortem --gate`` event chain)
    reconstructs from the surviving flight rings."""

    t: float
    kind: str        # 'kill_replica' | 'kill_router' | 'fault_burst'
    target: int = 0  # replica ordinal / router index / unused
    gate: str = ""


class IncidentScheduler:
    """Fires incidents when the virtual clock passes their ``t``.

    The loop runs on an injectable ``(clock, sleep)`` pair so tests
    drive it in fake time; each tick passes through the
    ``loadgen.tick`` fault point, so a chaos spec can delay or error
    the scheduler itself (a late incident injector is a production
    scenario too — chaos that arrives during recovery).
    """

    def __init__(self, incidents, time_scale=1.0,
                 clock=time.monotonic, sleep=time.sleep,
                 tick_s=0.05):
        self.incidents = sorted(incidents, key=lambda i: i.t)
        self.time_scale = float(time_scale)
        self.clock = clock
        self.sleep = sleep
        self.tick_s = float(tick_s)
        self.fired: list = []
        self.perturbed_ticks = 0
        self._stop = threading.Event()
        self._thread = None

    def run(self, fire) -> list:
        t0 = self.clock()
        pending = list(self.incidents)
        while pending and not self._stop.is_set():
            try:
                fault.inject("loadgen.tick",
                             detail=f"pending={len(pending)}")
            except fault.FaultInjected:
                self.perturbed_ticks += 1
            now_virtual = (self.clock() - t0) * self.time_scale
            while pending and pending[0].t <= now_virtual:
                inc = pending.pop(0)
                fire(inc)
                self.fired.append((round(now_virtual, 6), inc))
            if pending:
                self.sleep(self.tick_s)
        return self.fired

    def start(self, fire):
        self._thread = threading.Thread(target=self.run, args=(fire,),
                                        daemon=True)
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10.0)


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

class SoakHarness:
    """Subprocess fleet + schedule replay + incident verdicts.

    ``routers > 1`` spawns a leased HA tier (``--ha-dir``) so
    ``kill_router`` incidents are survivable; ``backend='process'``
    makes replicas real child processes so ``kill_replica`` is a true
    SIGKILL.  The chaos spec string goes to every subprocess via
    ``MXNET_FAULT_SPEC`` — fault bursts are armed there with
    ``after=``/``n=`` counters and verified post-hoc by their
    ``fault.<point>`` flight events.
    """

    def __init__(self, workdir, schedule, chaos_spec="",
                 incidents=(), routers=1, replicas=2,
                 backend="process", width=16, session_model="dec",
                 max_inflight=64, warmup=True):
        self.workdir = str(workdir)
        self.schedule = schedule
        self.chaos_spec = chaos_spec or ""
        self.incidents = tuple(incidents)
        self.routers = int(routers)
        self.replicas = int(replicas)
        self.backend = backend
        self.width = int(width)
        self.session_model = session_model
        self.max_inflight = int(max_inflight)
        self.warmup = bool(warmup)
        self.procs: list = []      # [(proc, port) or None (killed)]
        self.killed: set = set()
        self.errors: list = []
        self.recreates = 0
        self._err_lock = named_lock("loadgen.errors")
        self._prefix = None

    # -- fleet lifecycle -------------------------------------------------

    def _export(self):
        import jax.numpy as jnp
        from ... import deploy

        def fwd(params, x):
            y = x
            for w in params["layers"]:
                y = jnp.tanh(y @ w)
            return y

        rng = onp.random.RandomState(11)
        params = {"layers": [
            rng.randn(self.width, self.width).astype(onp.float32)
            * 0.1 for _ in range(2)]}
        x = rng.randn(1, self.width).astype(onp.float32)
        prefix = os.path.join(self.workdir, "soak_model")
        deploy.export_model(fwd, (x,), prefix, params=params,
                            aot_buckets=[1, 2, 4])
        return prefix

    def _env(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["MXNET_SERVING_SESSION_SNAPSHOT_STEPS"] = "2"
        env["MXNET_SERVING_BATCH_BUCKETS"] = "1,2,4"
        env["MXNET_SERVING_MAX_BATCH"] = "4"
        env["MXNET_FLIGHT_RING"] = "4096"
        env.pop("MXNET_FAULT_SPEC", None)
        if self.chaos_spec:
            env["MXNET_FAULT_SPEC"] = self.chaos_spec
        return env

    def _spawn_router(self, idx, prefix):
        models = sorted({a.model for a in self.schedule.arrivals
                         if a.kind == "predict"}) or ["bench"]
        cmd = [sys.executable, "-m",
               "incubator_mxnet_tpu.serving.router"]
        for m in models:
            cmd += ["--model", f"{m}={prefix}"]
        cmd += ["--session-model",
                f"{self.session_model}={SESSION_SPEC}",
                "--session-dir", os.path.join(self.workdir, "snaps"),
                "--replicas", str(self.replicas),
                "--backend", self.backend,
                "--host", "127.0.0.1", "--port", "0"]
        if not self.warmup:
            cmd.append("--no-warmup")
        if self.routers > 1:
            cmd += ["--ha-dir", os.path.join(self.workdir, "ha"),
                    "--router-id", f"soak-r{idx}",
                    "--lease-ttl", "1.0"]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env(), start_new_session=True,
            cwd=_REPO)
        port = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"soak router {idx} died at startup")
            if "routing on" in line:
                port = int(line.rsplit(":", 1)[1].split()[0])
                break
        if not port:
            raise RuntimeError(
                f"soak router {idx} never reported its port")
        # drain stdout so the pipe can't wedge the router
        threading.Thread(target=lambda: [None for _ in proc.stdout],
                         daemon=True).start()
        return proc, port

    def start(self):
        self._prefix = self._export()
        for idx in range(self.routers):
            self.procs.append(self._spawn_router(idx, self._prefix))
        return self

    def stop(self):
        for ent in self.procs:
            if ent is None:
                continue
            proc, _ = ent
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
        self.procs = [None] * len(self.procs)

    def live_ports(self) -> list:
        return [port for i, ent in enumerate(self.procs)
                if ent is not None and i not in self.killed
                for _, port in [ent]]

    def _live_port(self, k: int) -> int:
        ports = self.live_ports()
        if not ports:
            raise ConnectionError("no live soak router")
        return ports[k % len(ports)]

    # -- incident arms ---------------------------------------------------

    def replica_pids(self, router_idx=0) -> list:
        """With ``--backend process``, replicas are child server
        subprocesses of the router — read them off /proc."""
        ent = self.procs[router_idx]
        if ent is None:
            return []
        pids = []
        task_dir = f"/proc/{ent[0].pid}/task"
        try:
            for tid in os.listdir(task_dir):
                with open(f"{task_dir}/{tid}/children") as f:
                    pids.extend(int(p) for p in f.read().split())
        except OSError:
            pass
        return sorted(set(pids))

    def kill_replica(self, router_idx=0, which=0):
        pids = self.replica_pids(router_idx)
        if not pids:
            raise RuntimeError("no replica child pids to kill")
        os.kill(pids[which % len(pids)], signal.SIGKILL)
        return pids[which % len(pids)]

    def kill_router(self, idx):
        ent = self.procs[idx]
        if ent is None:
            return None
        os.killpg(ent[0].pid, signal.SIGKILL)
        ent[0].wait()
        self.killed.add(idx)
        return ent[0].pid

    def _fire(self, inc: Incident):
        try:
            if inc.kind == "kill_replica":
                self.kill_replica(router_idx=0, which=inc.target)
            elif inc.kind == "kill_router":
                self.kill_router(inc.target)
            # 'fault_burst' is pre-armed in the chaos spec (after=/n=)
            # — nothing to trigger here; the gate verifies it fired.
        except Exception as e:  # mxlint: allow-broad-except(incident arm: a misfire must land in the report, not kill the replay thread)
            with self._err_lock:
                self.errors.append(
                    f"incident {inc.kind}@{inc.t}: "
                    f"{type(e).__name__}: {e}")

    def warm(self):
        """Pre-warm every replica's predict + decode path (a few
        concurrent volleys so the router spreads them) — the replay
        then measures serving, not first-compile."""
        from .clients import sync_volley
        n = max(2 * self.replicas, 2)
        models = sorted({a.model for a in self.schedule.arrivals
                         if a.kind == "predict"}) or ["bench"]
        row = [0.05] * self.width
        for m in models:
            res = sync_volley(
                lambda i, m=m: PredictClient(
                    self._live_port(i), m)([row], deadline_s=90),
                n, clients=n)
            if res.errors:
                raise RuntimeError(
                    f"warmup predict volley failed for {m!r}: "
                    f"{res.errors[0][1]!r}")
        if any(a.kind == "session" for a in self.schedule.arrivals):
            srow = [0.05] * SESSION_DIM

            def sess(i):
                c = SessionClient(self._live_port(i),
                                  self.session_model, f"warm{i}")
                c.create(deadline_s=90)
                c.step([srow], 2)
                c.close()

            res = sync_volley(sess, n, clients=n)
            if res.errors:
                raise RuntimeError(
                    f"warmup session volley failed: "
                    f"{res.errors[0][1]!r}")
        return self

    # -- replay ----------------------------------------------------------

    def _note_error(self, what, e):
        with self._err_lock:
            self.errors.append(f"{what}: {type(e).__name__}: {e}")

    def _run_predict(self, arr, monitor, t0):
        cli = PredictClient(self._live_port(arr.client), arr.model,
                            slo=arr.slo)
        row = [arr.value] * self.width
        t1 = time.monotonic()
        try:
            code, _ = cli([row], deadline_s=45)
            ok = code == 200
        except (TimeoutError, urllib.error.HTTPError,
                ConnectionError, OSError) as e:
            ok = False
            self._note_error(f"predict c{arr.client}", e)
        ms = (time.monotonic() - t1) * 1000.0
        monitor.observe((time.monotonic() - t0)
                        * self.schedule.time_scale,
                        arr.slo, ms, ok=ok)

    def _recreate(self, cli, deadline_s=30):
        """Close + re-create a session (replay-from-zero path); one
        retry covers a close the server hadn't applied yet."""
        cli.close()
        try:
            cli.create(deadline_s=deadline_s)
        except urllib.error.HTTPError:
            cli.close()
            time.sleep(0.2)
            cli.create(deadline_s=deadline_s)

    def _run_session(self, arr, ledger, monitor, t0):
        sid = f"s{arr.client}"
        ledger.expect(sid, arr.steps, arr.value)
        cli = SessionClient(self._live_port(arr.client),
                            self.session_model, sid, slo=arr.slo)
        row = [arr.value] * SESSION_DIM
        deadline = time.monotonic() + 120
        try:
            cli.create(deadline_s=45)
        except (TimeoutError, ConnectionError,
                urllib.error.HTTPError) as e:
            self._note_error(f"session {sid} create", e)
            return
        done = 0
        while done < arr.steps and time.monotonic() < deadline:
            k = min(4, arr.steps - done)
            t1 = time.monotonic()
            try:
                base, chunks, timing = cli.step([row], k, stream=True)
            except StreamBroken:
                # visible break: re-target a live router and retry —
                # the server re-bases from its last durable snapshot
                cli.port = self._live_port(arr.client + 1)
                time.sleep(0.25)
                continue
            except urllib.error.HTTPError as e:
                if e.code == 410:      # session lost: recreate+replay
                    self.recreates += 1
                    cli.recreates += 1
                    cli.port = self._live_port(arr.client + 1)
                    try:
                        self._recreate(cli)
                    except (TimeoutError, ConnectionError,
                            urllib.error.HTTPError) as e2:
                        self._note_error(f"session {sid} recreate",
                                         e2)
                        return
                    done = 0
                    continue
                if e.code in (503, 429):    # draining / shed: retry
                    cli.port = self._live_port(arr.client + 1)
                    time.sleep(0.25)
                    continue
                self._note_error(f"session {sid} step", e)
                return
            except (TimeoutError, ConnectionError, OSError) as e:
                cli.port = self._live_port(arr.client + 1)
                self._note_error(f"session {sid} step", e)
                time.sleep(0.25)
                continue
            ms = (time.monotonic() - t1) * 1000.0
            monitor.observe((time.monotonic() - t0)
                            * self.schedule.time_scale,
                            arr.slo, ms, ok=True)
            # never record past the reference length (a re-based
            # replay can overshoot the target step count)
            ledger.record(sid, base, chunks[:max(0, arr.steps - base)])
            if base > done:
                # a broken attempt's steps executed server-side but
                # were never delivered — the gap can only be refilled
                # by replaying the (deterministic) session from zero
                try:
                    self._recreate(cli)
                except (TimeoutError, ConnectionError,
                        urllib.error.HTTPError) as e:
                    self._note_error(f"session {sid} gap-replay", e)
                    return
                self.recreates += 1
                done = 0
                continue
            done = int(timing.get("session_steps", base + k))
        if done < arr.steps:
            self._note_error(f"session {sid}",
                             TimeoutError(
                                 f"stalled at {done}/{arr.steps}"))
        cli.close()

    def _references(self, ledger) -> dict:
        from ..sessions import SessionManager, toy_decoder
        mgr = SessionManager("soakref",
                             toy_decoder(dim=SESSION_DIM, max_len=64),
                             buckets=[1], warmup=False)
        refs = {}
        for sid, meta in sorted(ledger.meta().items()):
            mgr.create(sid)
            chunks, _ = mgr.step(
                sid, (onp.full(SESSION_DIM, meta["value"],
                               onp.float32),),
                steps=meta["steps"])
            mgr.close(sid)
            refs[sid] = [onp.asarray(c[0]) for c in chunks]
        return refs

    def gate_incidents(self) -> list:
        """Run ``postmortem --gate`` for every incident that declared
        a chain, against every surviving router's flight ring."""
        sources = [f"http://127.0.0.1:{p}/v1/flight"
                   for p in self.live_ports()]
        results = []
        for inc in self.incidents:
            if not inc.gate:
                continue
            r = subprocess.run(
                [sys.executable, POSTMORTEM, "--gate", inc.gate]
                + sources, capture_output=True, text=True,
                cwd=_REPO, timeout=120)
            results.append({"t": inc.t, "kind": inc.kind,
                            "gate": inc.gate,
                            "gate_ok": r.returncode == 0,
                            "detail": (r.stdout + r.stderr)
                            .strip()[-400:]})
        return results

    def run(self) -> dict:
        """Replay the schedule against the running fleet; returns the
        full soak report (callers assert on it)."""
        monitor = SloMonitor()
        ledger = StreamLedger()
        scheduler = IncidentScheduler(self.incidents,
                                      self.schedule.time_scale)
        threads: list = []
        gate = threading.Semaphore(self.max_inflight)

        def dispatch(arr):
            try:
                if arr.kind == "session":
                    self._run_session(arr, ledger, monitor, t0)
                else:
                    self._run_predict(arr, monitor, t0)
            finally:
                gate.release()

        t0 = time.monotonic()
        if self.incidents:
            scheduler.start(self._fire)
        for arr in self.schedule.arrivals:
            wait = self.schedule.real_time(arr.t) \
                - (time.monotonic() - t0)
            if wait > 0:
                time.sleep(wait)
            gate.acquire()
            th = threading.Thread(target=dispatch, args=(arr,),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(180)
        scheduler.stop()

        stream_failures = ledger.verify(self._references(ledger))
        lost = len({f["sid"] for f in stream_failures})
        metrics = {}
        for port in self.live_ports():
            try:
                parsed = parse_prometheus(scrape(port))
            except (OSError, ConnectionError):
                continue
            metrics = {
                "requests_200": metric_sum(
                    parsed, "mxnet_serving_fleet_requests_total",
                    code="200"),
                "session_losses": metric_sum(
                    parsed,
                    "mxnet_serving_fleet_session_losses_total"),
                "session_migrations": metric_sum(
                    parsed,
                    "mxnet_serving_fleet_session_migrations_total"),
                "exemplars": len(parsed["exemplars"]),
            }
            break
        report = dict(self.schedule.describe())
        report.update({
            "chaos_spec": self.chaos_spec,
            "slo": monitor.report(),
            "slo_header": SLO_HEADER,
            "sessions": len(ledger.meta()),
            "lost_streams": lost,
            "stream_failures": stream_failures[:8],
            "recreates": self.recreates,
            "errors": sorted(self.errors)[:8],
            "error_count": len(self.errors),
            "perturbed_ticks": scheduler.perturbed_ticks,
            "incidents": self.gate_incidents(),
            "metrics": metrics,
        })
        return report

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def main():  # pragma: no cover - exercised via benchmark/soak_bench.py
    raise SystemExit(
        "use benchmark/soak_bench.py to drive the soak harness")


if __name__ == "__main__":
    main()
