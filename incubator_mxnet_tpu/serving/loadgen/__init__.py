"""Workload-replay + capacity-planning harness (docs/capacity.md).

The bench suite gates point floors; this package is the subsystem that
replays *production-shaped* traffic — diurnal ramps, flash crowds,
heavy-tailed session lengths, multi-tenant mixes, adversarial
burst-on-shrink — as closed-loop clients against a real fleet under a
seeded chaos spec, and asserts the north-star claim with the
observability stack: per-class SLO conformance from ``/metrics`` +
exemplars, ``tools/postmortem.py --gate`` for every injected incident,
and zero lost streams (bitwise).

Modules:

* :mod:`.workload` — declarative, seeded workload specs that compile
  to a deterministic virtual-time arrival schedule (same seed ⇒ same
  schedule, bit for bit; a ``time_scale`` knob compresses replay).
* :mod:`.clients`  — the closed-loop client machinery every bench
  shares (volley engines, duration phases, HTTP predict/session
  clients with per-request SLO-class headers).
* :mod:`.harness`  — subprocess fleet under chaos with scheduled
  incident injection (SIGKILL replica/router at *t*), the
  ``/metrics`` conformance reader, the zero-lost-streams ledger and
  the postmortem gate driver.
* :mod:`.capacity` — offered-load x replica-count sweeps emitting the
  capacity curve (offered QPS vs replicas at SLO) with knee detection.
"""
from .workload import (Arrival, Schedule, WorkloadSpec,  # noqa: F401
                       parse_workload, pareto_steps)
from .clients import (percentile, sync_volley, wave_volley,  # noqa: F401
                      VolleyResult, ClosedLoopPhase,
                      PredictClient, SessionClient, StreamBroken,
                      post_json, post_retry, scrape, SLO_HEADER,
                      provenance)
from .harness import (Incident, IncidentScheduler,  # noqa: F401
                      SloMonitor, StreamLedger, SoakHarness,
                      parse_prometheus, slo_targets)
from .capacity import sweep_capacity, find_knee  # noqa: F401
