"""Closed-loop client machinery shared by every bench and the soak
harness.

Before this module, ``benchmark/serving_bench.py``,
``session_bench.py`` and ``autoscale_bench.py`` each carried a
near-duplicate copy of the same volley engine (bounds-split client
threads behind a start barrier, latency + error collection).  The one
implementation lives here now; the benches are thin scenario drivers
on top of it.

Three engines, one per traffic shape the benches need:

* :func:`sync_volley`  — N requests x R rounds of synchronous calls,
  per-request latency (the fleet/overhead volleys).
* :func:`wave_volley`  — async submit-then-resolve waves with
  whole-wave latency (the dynamic-batching volley, where per-handle
  latency would measure CPython thread wakeups, not the server).
* :class:`ClosedLoopPhase` — duration-based closed loop with SLO shed
  accounting (the autoscale trace phases).

Plus the HTTP clients the soak harness replays workloads through:
:class:`PredictClient` and :class:`SessionClient` speak the router's
wire API with per-request SLO-class headers (``X-MXNET-SLO-CLASS``)
and bounded retry over failover/takeover windows.
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

from ...locks import named_lock

__all__ = ["percentile", "VolleyResult", "sync_volley", "wave_volley",
           "ClosedLoopPhase", "post_json", "post_retry", "scrape",
           "PredictClient", "SessionClient", "StreamBroken",
           "SLO_HEADER", "provenance"]


def provenance(workload, seed):
    """The reproduction keys every bench/harness JSON artifact
    records (reproduction discipline: a failure replays from the
    artifact alone): the workload name, the seed, and whatever chaos
    spec was live in the environment."""
    return {"workload": str(workload), "seed": int(seed),
            "chaos_spec": os.environ.get("MXNET_FAULT_SPEC", "")}

#: Per-request SLO-class tag: clients label every request with the
#: class they expect conformance against, so a front end (or a future
#: per-request admission path) can tell tiers apart on the wire.
SLO_HEADER = "X-MXNET-SLO-CLASS"


def percentile(latencies, q):
    """Nearest-rank percentile (0 for an empty sample)."""
    data = sorted(latencies)
    if not data:
        return 0.0
    return data[min(len(data) - 1, int(q * len(data)))]


class VolleyResult:
    """What a volley measured: throughput, latencies, results, errors.

    ``errors`` is a list of ``(index, exception)`` tuples — callers
    decide whether an error fails the bench or is an expected shed.
    """

    def __init__(self, rps, total_s, results, lat_ms, errors):
        self.rps = rps
        self.total_s = total_s
        self.results = results
        self.lat_ms = lat_ms
        self.errors = errors

    def p99_ms(self):
        return percentile(self.lat_ms, 0.99)


def _client_bounds(n, clients):
    """Split indices 0..n-1 across client threads, remainder spread
    over the first few — dropping leftovers would overstate rps and
    leave result rows unverified."""
    nclients = max(1, min(clients, n))
    return nclients, [n * c // nclients for c in range(nclients + 1)]


def sync_volley(call, n, rounds=1, clients=8, collect_latency=True,
                stop_on_error=True):
    """Closed-loop synchronous volley: ``call(i)`` for every index,
    ``rounds`` times, across ``clients`` threads behind one start
    barrier.  Per-request latency; the wall clock starts when the
    barrier releases, so thread spawn time is off-clock."""
    nclients, bounds = _client_bounds(n, clients)
    results = [None] * n
    lat, errors = [], []
    lock = named_lock("loadgen.closed")
    barrier = threading.Barrier(nclients + 1)

    def client(c):
        barrier.wait()
        mine = []
        for _ in range(rounds):
            for i in range(bounds[c], bounds[c + 1]):
                t1 = time.monotonic()
                try:
                    results[i] = call(i)
                except Exception as e:  # mxlint: allow-broad-except(volley engine: every failure is collected into VolleyResult.errors for the caller's verdict)
                    with lock:
                        errors.append((i, e))
                    if stop_on_error:
                        return
                    continue
                if collect_latency:
                    mine.append((time.monotonic() - t1) * 1000.0)
        if mine:
            with lock:
                lat.extend(mine)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(nclients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    dt = time.monotonic() - t0
    return VolleyResult(n * rounds / dt, dt, results, lat, errors)


def wave_volley(submit, n, rounds=1, clients=8, resolve=None):
    """Async wave volley: each client submits handles for its whole
    index range, then resolves them — the shape an async HTTP front
    end gives a dynamic batcher.  Latency is whole-wave per index
    (one OS thread per request would measure CPython thread wakeups,
    not the serving stack)."""
    resolve = resolve or (lambda h: h.result())
    nclients, bounds = _client_bounds(n, clients)
    results = [None] * n
    lat, errors = [], []
    lock = named_lock("loadgen.waves")
    barrier = threading.Barrier(nclients + 1)

    def client(c):
        barrier.wait()
        mine = []
        for _ in range(rounds):
            t1 = time.monotonic()
            ids = range(bounds[c], bounds[c + 1])
            try:
                handles = [(i, submit(i)) for i in ids]
                for i, h in handles:
                    results[i] = resolve(h)
            except Exception as e:  # mxlint: allow-broad-except(volley engine: every failure is collected into VolleyResult.errors for the caller's verdict)
                with lock:
                    errors.append((bounds[c], e))
                return
            dt_ms = (time.monotonic() - t1) * 1000.0
            mine.extend([dt_ms] * len(ids))       # whole-wave latency
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(nclients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    dt = time.monotonic() - t0
    return VolleyResult(n * rounds / dt, dt, results, lat, errors)


class ClosedLoopPhase:
    """Duration-based closed-loop clients with SLO shed accounting —
    one trace phase of the autoscale bench, or one plateau of a soak.

    ``route(model, x)`` is the request; shed (429 / placement
    backpressure) is counted separately from organic errors because
    shedding the batch tier is the SLO contract's *explicit* arm while
    any interactive shed fails the trace.
    """

    def __init__(self, route, width):
        self.route = route
        self.width = width
        self.lat_ms = {}      # model -> [ms]
        self.errors = {}      # model -> [repr]
        self.shed = {}        # model -> count (429/503 — the SLO arm)
        self._lock = named_lock("loadgen.mixed")

    def _client(self, model, stop, rng):
        from ..admission import QueueFullError
        x = rng.randn(self.width).astype("float32")
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                self.route(model, x)
                ms = (time.monotonic() - t0) * 1000.0
                with self._lock:
                    self.lat_ms.setdefault(model, []).append(ms)
            except (QueueFullError, ConnectionError) as e:
                # shed / placement backpressure: counted, and fatal
                # for the interactive tier
                with self._lock:
                    self.shed[model] = self.shed.get(model, 0) + 1
                    self.errors.setdefault(model, []).append(
                        type(e).__name__)
                time.sleep(0.005)
            except Exception as e:  # mxlint: allow-broad-except(bench harness: every failure lands in the per-model error list, which fails --check)
                with self._lock:
                    self.errors.setdefault(model, []).append(
                        f"{type(e).__name__}: {e}")
                time.sleep(0.005)

    def run(self, clients, duration_s, seed=7):
        import numpy as onp
        stop = threading.Event()
        threads = []
        for i, model in enumerate(clients):
            rng = onp.random.RandomState(seed + i)
            t = threading.Thread(target=self._client,
                                 args=(model, stop, rng), daemon=True)
            t.start()
            threads.append(t)
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(10.0)
        return self


# ---------------------------------------------------------------------------
# HTTP clients (router wire API)
# ---------------------------------------------------------------------------

def post_json(port, path, body, headers=None, timeout=60):
    """One JSON POST against a local router/server; returns
    ``(status, parsed_body)``."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def post_retry(port, path, body, deadline_s=30, headers=None,
               retry_codes=(503,), backoff_s=0.25):
    """POST with bounded retry over a failover/takeover window: 503s
    and refused sockets are the EXPECTED transient while a dead
    replica quarantines or a dead router's lease ages out — a lost
    request is anything that still fails past the deadline."""
    end = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < end:
        try:
            return post_json(port, path, body, headers=headers,
                             timeout=60)
        except urllib.error.HTTPError as e:
            last = e
            if e.code not in retry_codes:
                raise
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = e
        time.sleep(backoff_s)
    raise TimeoutError(
        f"request {path} did not land within {deadline_s}s: {last!r}")


def scrape(port, path="/metrics", timeout=30):
    """GET a text endpoint (``/metrics``) and return the body."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.read().decode()


class StreamBroken(ConnectionError):
    """A chunked session stream broke before its ``done`` terminator
    (replica or router died mid-relay): the chunks received cannot be
    placed at absolute step indices, so the caller retries the step —
    the server re-bases from its last durable snapshot."""


class PredictClient:
    """Closed-loop predict client tagging every request with its SLO
    class; retries over failover windows via :func:`post_retry`."""

    def __init__(self, port, model, slo="standard"):
        self.port = port
        self.model = model
        self.slo = slo

    def __call__(self, inputs, timeout_ms=None, deadline_s=30):
        body = {"inputs": [x.tolist() if hasattr(x, "tolist") else x
                           for x in inputs]}
        if timeout_ms is not None:
            body["timeout_ms"] = timeout_ms
        code, out = post_retry(
            self.port, f"/v1/models/{self.model}:predict", body,
            deadline_s=deadline_s, headers={SLO_HEADER: self.slo},
            backoff_s=0.1)
        return code, out


class SessionClient:
    """Session-stream client: creates a session, steps it in chunks,
    and yields only COMPLETED steps (a broken stream surfaces as
    :class:`StreamBroken`; the retry re-bases server-side).

    Every completed step reports ``(base, chunks, timing)`` where
    ``base = session_steps - steps`` — the absolute index of the first
    chunk, which is what makes the zero-lost-streams ledger's bitwise
    coverage check possible across migrations and re-bases.
    """

    def __init__(self, port, model, sid, slo="interactive"):
        self.port = port
        self.model = model
        self.sid = sid
        self.slo = slo
        self.recreates = 0

    def _headers(self):
        return {SLO_HEADER: self.slo}

    def create(self, deadline_s=30):
        code, _ = post_retry(
            self.port, f"/v1/sessions/{self.model}:create",
            {"session_id": self.sid}, deadline_s=deadline_s,
            headers=self._headers())
        if code != 200:
            raise ConnectionError(
                f"session {self.sid!r} create answered {code}")

    def step(self, inputs, steps, stream=False, deadline_s=45):
        """One decode call of ``steps`` steps.  Returns
        ``(base, chunks, timing)`` for a COMPLETED call; raises
        :class:`StreamBroken` on a mid-stream break and
        :class:`SessionLost` (as ConnectionError subclass via 410)
        handling is the caller's: a 410 Gone re-raises as-is."""
        body = {"inputs": [x.tolist() if hasattr(x, "tolist") else x
                           for x in inputs], "steps": steps}
        if not stream:
            code, d = post_retry(
                self.port,
                f"/v1/sessions/{self.model}/{self.sid}:step", body,
                deadline_s=deadline_s, headers=self._headers())
            timing = d["timing"]
            base = int(timing["session_steps"]) - int(d["steps"])
            return base, d["outputs"], timing
        body["stream"] = True
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1/sessions/"
            f"{self.model}/{self.sid}:step",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **self._headers()})
        lines = []
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                for raw in resp:
                    raw = raw.strip()
                    if raw:
                        lines.append(json.loads(raw))
        except urllib.error.HTTPError:
            # a typed HTTP verdict (410 session-lost, 503 draining) is
            # NOT a broken stream — the caller's error mapping owns it
            raise
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise StreamBroken(
                f"stream of {self.sid!r} broke after "
                f"{len(lines)} line(s): {type(e).__name__}") from e
        done = lines[-1] if lines else {}
        if not done.get("done"):
            # an in-band typed error line or a truncation: either way
            # the step did not complete — visible, never silent
            raise StreamBroken(
                f"stream of {self.sid!r} ended without its done "
                f"terminator ({done.get('error') or 'truncated'})")
        timing = done.get("timing", {})
        chunks = [ln["outputs"] for ln in lines[:-1]]
        base = int(timing["session_steps"]) - int(done["steps"])
        return base, chunks, timing

    def close(self, deadline_s=15):
        try:
            post_retry(self.port,
                       f"/v1/sessions/{self.model}/{self.sid}:close",
                       {}, deadline_s=deadline_s,
                       headers=self._headers())
        except (TimeoutError, urllib.error.HTTPError):
            pass   # close is best-effort; TTL reaps stragglers
