"""Declarative, seeded workload specs → deterministic schedules.

A :class:`WorkloadSpec` names a traffic *shape* (diurnal ramp, flash
crowd, steady, multi-tenant mix, burst-on-shrink) plus its parameters,
and :meth:`WorkloadSpec.compile` turns it into a concrete per-client
arrival schedule in **virtual seconds**.  Everything is drawn from one
``random.Random(seed)``: the same spec string + seed reproduce the
same schedule bit for bit, on any host — a soak failure replays from
the ``(workload, seed, time_scale, chaos_spec)`` quadruple alone.

Virtual vs real time: the schedule is laid out in virtual seconds and
never consults a clock.  At replay, ``time_scale`` compresses it —
``t_real = t_virtual / time_scale`` — so a 30-minute diurnal window
can drive a CI-sized run in seconds.  Rates compress accordingly: a
shape offering R virtual-QPS replays at ``R * time_scale`` real QPS
(docs/capacity.md "Time compression").

Spec grammar (the string recorded in every JSON artifact)::

    workload := shape [':' key '=' value (',' key '=' value)*]
    shape    := steady | diurnal | flash_crowd | multi_tenant
                | burst_on_shrink
    keys     := duration   virtual seconds               (default 30)
                base       baseline virtual QPS          (default 4)
                peak       peak virtual QPS              (shapes with
                                                          a peak)
                cycles     diurnal peak count            (default 1)
                peak_at    flash-crowd center, 0..1      (default 0.5)
                peak_width flash-crowd width, 0..1       (default 0.2)
                quiet      burst_on_shrink trough QPS    (default 0)
                sessions   fraction of arrivals that are
                           session streams, 0..1         (default 0)
                steps_alpha/steps_min/steps_cap
                           bounded-Pareto session-length
                           draw parameters               (1.2 / 4 / 48)
                tenants    '+'-joined NAME@CLASS*WEIGHT
                           entries (default bench@standard)

Example::

    flash_crowd:duration=24,base=4,peak=24,sessions=0.25,
    tenants=hi@interactive*3+lo@batch*1
"""
from __future__ import annotations

import hashlib
import json
import math
import random
import time
from typing import Callable, NamedTuple

from ...base import get_env

__all__ = ["Arrival", "Schedule", "WorkloadSpec", "parse_workload",
           "pareto_steps", "SHAPES"]

# the single permitted wall-clock anchor: stamps replay artifacts with
# a human-readable start; NEVER used in scheduling math (the schedule
# is pure virtual time, replay maps it onto time.monotonic)
_ANCHOR_WALL = time.time()  # mxlint: allow-wall-clock(one-time artifact stamp; scheduling math is virtual-time + monotonic only)


class Arrival(NamedTuple):
    """One scheduled client arrival, in virtual seconds."""

    t: float          # virtual arrival time (seconds from replay start)
    client: int       # stable client id (0-based, arrival order)
    kind: str         # 'predict' | 'session'
    model: str        # tenant model name
    slo: str          # SLO class the client tags its requests with
    steps: int        # session decode steps (0 for predict)
    value: float      # deterministic per-client payload scalar


def pareto_steps(rng: random.Random, alpha: float = 1.2,
                 xmin: int = 4, cap: int = 48) -> int:
    """Bounded-Pareto session length: inverse-CDF draw clamped to
    ``[xmin, cap]``.  Heavy-tailed by construction — most sessions are
    short, a fat tail pins the continuous batcher's long-stream path —
    and fully determined by ``rng``'s state (no numpy, no platform
    variance)."""
    u = rng.random()
    x = xmin / ((1.0 - u) ** (1.0 / alpha))
    return int(min(cap, max(xmin, math.floor(x))))


# ---------------------------------------------------------------------------
# rate shapes: virtual QPS as a function of virtual time
# ---------------------------------------------------------------------------

def _rate_steady(p: dict) -> Callable[[float], float]:
    return lambda t: p["base"]


def _rate_diurnal(p: dict) -> Callable[[float], float]:
    """Smooth trough→peak→trough ramp(s): the stated production shape.
    ``cycles`` peaks across the window, raised-cosine so the ramp has
    no step discontinuities for a predictive policy to cheat on."""
    span = max(p["peak"] - p["base"], 0.0)

    def rate(t):
        phase = 2.0 * math.pi * p["cycles"] * t / p["duration"]
        return p["base"] + span * 0.5 * (1.0 - math.cos(phase))
    return rate


def _rate_flash_crowd(p: dict) -> Callable[[float], float]:
    """Baseline with one sharp crowd: a linear spike-up over the first
    tenth of the burst window, a hold at ``peak``, and a hard drop —
    the shape that punishes slow scale-out and queue shed ladders."""
    center = p["peak_at"] * p["duration"]
    half = 0.5 * p["peak_width"] * p["duration"]
    ramp = max(0.1 * p["peak_width"] * p["duration"], 1e-9)

    def rate(t):
        if abs(t - center) > half:
            return p["base"]
        lead = t - (center - half)
        if lead < ramp:
            return p["base"] + (p["peak"] - p["base"]) * lead / ramp
        return p["peak"]
    return rate


def _rate_burst_on_shrink(p: dict) -> Callable[[float], float]:
    """Adversarial for the autoscaler: burst, a quiet trough long
    enough to trigger shrink/unload, then an instant second burst that
    lands exactly on the shrunk fleet."""
    third = p["duration"] / 3.0

    def rate(t):
        if t < third:
            return p["peak"]
        if t < 2.0 * third:
            return p["quiet"]
        return p["peak"]
    return rate


SHAPES = {
    "steady": _rate_steady,
    "multi_tenant": _rate_steady,   # the mix lives in `tenants`
    "diurnal": _rate_diurnal,
    "flash_crowd": _rate_flash_crowd,
    "burst_on_shrink": _rate_burst_on_shrink,
}

_DEFAULTS = {"duration": 30.0, "base": 4.0, "peak": 16.0,
             "cycles": 1.0, "peak_at": 0.5, "peak_width": 0.2,
             "quiet": 0.0, "sessions": 0.0,
             "steps_alpha": 1.2, "steps_min": 4, "steps_cap": 48}


class Schedule:
    """A compiled arrival schedule: pure virtual-time data.

    ``arrivals`` is a tuple of :class:`Arrival` sorted by ``t``.  The
    schedule is a value object — :meth:`fingerprint` hashes its exact
    contents, and the soak gate's determinism check compares two
    independent compiles bit for bit.
    """

    def __init__(self, spec: "WorkloadSpec", seed: int,
                 time_scale: float, arrivals: tuple):
        self.spec = spec
        self.seed = int(seed)
        self.time_scale = float(time_scale)
        self.arrivals = arrivals

    def real_time(self, t_virtual: float) -> float:
        """Replay offset in real seconds for a virtual timestamp."""
        return t_virtual / self.time_scale

    @property
    def duration_virtual_s(self) -> float:
        return self.spec.params["duration"]

    @property
    def duration_real_s(self) -> float:
        return self.real_time(self.duration_virtual_s)

    def fingerprint(self) -> str:
        """sha256 over the canonical schedule contents — the
        bit-for-bit determinism witness recorded in soak artifacts."""
        blob = json.dumps(
            {"workload": self.spec.describe(), "seed": self.seed,
             "time_scale": self.time_scale,
             "arrivals": [[round(a.t, 9), a.client, a.kind, a.model,
                           a.slo, a.steps, round(a.value, 9)]
                          for a in self.arrivals]},
            sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    def by_kind(self, kind: str):
        return [a for a in self.arrivals if a.kind == kind]

    def minutes(self) -> int:
        """Virtual-minute bin count (SLO conformance is per-minute)."""
        return max(1, math.ceil(self.duration_virtual_s / 60.0))

    def describe(self) -> dict:
        """The reproduction block every JSON artifact embeds."""
        return {"workload": self.spec.describe(), "seed": self.seed,
                "time_scale": self.time_scale,
                "arrivals": len(self.arrivals),
                "fingerprint": self.fingerprint(),
                "anchored_at": round(_ANCHOR_WALL, 3)}


class WorkloadSpec:
    """A named traffic shape + parameters; see the module grammar."""

    def __init__(self, shape: str, params: dict | None = None,
                 tenants: tuple | None = None):
        if shape not in SHAPES:
            raise ValueError(
                f"unknown workload shape {shape!r} "
                f"(known: {', '.join(sorted(SHAPES))})")
        self.shape = shape
        self.params = dict(_DEFAULTS)
        self.params.update(params or {})
        # (model, slo_class, weight) — the multi-tenant mix
        self.tenants = tuple(tenants or (("bench", "standard", 1.0),))
        if self.shape == "multi_tenant" and len(self.tenants) < 2:
            raise ValueError("multi_tenant shape needs >= 2 tenants")
        for _, slo, w in self.tenants:
            if w <= 0:
                raise ValueError(f"tenant weight must be > 0, got {w}")

    def describe(self) -> str:
        """Canonical spec string (round-trips through
        :func:`parse_workload`)."""
        keys = sorted(k for k in self.params
                      if self.params[k] != _DEFAULTS.get(k))
        opts = [f"{k}={self.params[k]:g}" for k in keys]
        opts.append("tenants=" + "+".join(
            f"{m}@{s}*{w:g}" for m, s, w in self.tenants))
        return f"{self.shape}:" + ",".join(opts)

    def rate_fn(self) -> Callable[[float], float]:
        return SHAPES[self.shape](self.params)

    def compile(self, seed: int | None = None,
                time_scale: float | None = None) -> Schedule:
        """Compile to a deterministic schedule.

        Arrivals come from an inhomogeneous Poisson process (thinning
        against the shape's peak rate); tenancy, kind and session
        length are further draws from the SAME seeded stream, so the
        whole schedule is one function of ``(spec, seed)``.  No clock
        is consulted — compile is pure.
        """
        seed = int(get_env("MXNET_SOAK_SEED", 7, int)
                   if seed is None else seed)
        time_scale = float(get_env("MXNET_SOAK_TIME_SCALE", 1.0, float)
                           if time_scale is None else time_scale)
        if time_scale <= 0:
            raise ValueError(f"time_scale must be > 0, got {time_scale}")
        p = self.params
        rate = self.rate_fn()
        rate_max = max(rate(p["duration"] * k / 512.0)
                       for k in range(513))
        rng = random.Random(seed)
        weights = [w for _, _, w in self.tenants]
        wsum = sum(weights)
        arrivals = []
        t = 0.0
        client = 0
        while True:
            if rate_max <= 0:
                break
            t += rng.expovariate(rate_max)       # thinning envelope
            if t >= p["duration"]:
                break
            if rng.random() * rate_max >= rate(t):
                continue                          # thinned out
            pick = rng.random() * wsum
            acc = 0.0
            model, slo = self.tenants[-1][0], self.tenants[-1][1]
            for m, s, w in self.tenants:
                acc += w
                if pick < acc:
                    model, slo = m, s
                    break
            is_session = rng.random() < p["sessions"]
            steps = (pareto_steps(rng, p["steps_alpha"],
                                  int(p["steps_min"]),
                                  int(p["steps_cap"]))
                     if is_session else 0)
            value = round(0.02 + 0.18 * rng.random(), 6)
            arrivals.append(Arrival(
                t=t, client=client,
                kind="session" if is_session else "predict",
                model=model, slo=slo, steps=steps, value=value))
            client += 1
        return Schedule(self, seed, time_scale, tuple(arrivals))


def parse_workload(spec: str) -> WorkloadSpec:
    """Parse the grammar in the module docstring into a
    :class:`WorkloadSpec` (the inverse of :meth:`~WorkloadSpec.describe`)."""
    shape, sep, rest = spec.partition(":")
    shape = shape.strip()
    params: dict = {}
    tenants = None
    if sep and rest.strip():
        for opt in rest.split(","):
            opt = opt.strip()
            if not opt:
                continue
            k, sep2, v = opt.partition("=")
            if not sep2:
                raise ValueError(
                    f"workload option {opt!r}: want key=value")
            if k == "tenants":
                tenants = []
                for ent in v.split("+"):
                    name, sep3, rest3 = ent.partition("@")
                    if not sep3 or not name:
                        raise ValueError(
                            f"tenant entry {ent!r}: want "
                            f"NAME@CLASS[*WEIGHT]")
                    slo, _, w = rest3.partition("*")
                    tenants.append((name, slo or "standard",
                                    float(w) if w else 1.0))
                tenants = tuple(tenants)
            elif k in _DEFAULTS:
                params[k] = (int(v) if k in ("steps_min", "steps_cap")
                             else float(v))
            else:
                raise ValueError(
                    f"unknown workload option {k!r} "
                    f"(known: {', '.join(sorted(_DEFAULTS))}, tenants)")
    return WorkloadSpec(shape, params, tenants)
