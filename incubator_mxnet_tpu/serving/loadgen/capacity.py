"""Capacity planning: offered-load x replica-count sweeps.

The north-star claim ("serves heavy traffic from millions of users")
becomes a measured curve here: for each replica count, calibrate the
fleet's closed-loop ceiling, then probe open-loop offered rates
against the SLO targets and record which offered points CONFORM
(achieved/offered >= 0.9, in-run p99 under the class target, zero
errors).  :func:`find_knee` reduces the curve to the per-replica
capacity and the knee — the replica count past which marginal
capacity stops scaling (docs/capacity.md "Reading a capacity curve").

The sweep runs in-process (thread-backend fleet, direct
``router.route``) so its numbers measure the serving stack, not HTTP
parsing; the chaos-laden subprocess verdict is the harness's job.
"""
from __future__ import annotations

import threading
import time

import numpy as onp

from ...locks import named_lock
from .clients import percentile, sync_volley
from .harness import slo_targets

__all__ = ["sweep_capacity", "find_knee", "open_loop"]


def open_loop(call, rate, n, max_inflight=32, join_s=60.0):
    """Offer ``n`` requests at a constant ``rate``/s regardless of
    completions (open loop — the arrival process does not slow down
    when the server queues, which is what saturates a fleet the way
    production traffic does).  Returns achieved rps / p99 / errors."""
    lat, errors = [], []
    lock = named_lock("loadgen.capacity")
    sem = threading.Semaphore(max_inflight)
    threads = []
    t0 = time.monotonic()
    for i in range(n):
        wait = i / rate - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        sem.acquire()

        def one(i=i):
            t1 = time.monotonic()
            try:
                call(i)
                with lock:
                    lat.append((time.monotonic() - t1) * 1000.0)
            except Exception as e:  # mxlint: allow-broad-except(sweep probe: failures are the measurement — they mark the offered point non-conformant)
                with lock:
                    errors.append((i, f"{type(e).__name__}: {e}"))
            finally:
                sem.release()

        th = threading.Thread(target=one, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(join_s)
    elapsed = max(time.monotonic() - t0, 1e-9)
    return {"achieved_rps": len(lat) / elapsed,
            "p99_ms": percentile(lat, 0.99),
            "completed": len(lat),
            "errors": len(errors),
            "error_sample": [e for _, e in errors[:3]]}


def sweep_capacity(prefix, replica_counts=(1, 2),
                   load_fractions=(0.25, 0.5, 1.0), requests=48,
                   clients=8, width=16, model="bench",
                   slo_class="standard", backend="thread"):
    """Sweep offered load across replica counts against the exported
    artifact at ``prefix``.  Returns the capacity-curve dict the soak
    bench embeds: per-point conformance plus the knee reduction."""
    from .. import FleetRouter, ReplicaFleet

    target_ms = slo_targets().get(slo_class)
    rng = onp.random.RandomState(3)
    xs = [rng.randn(width).astype(onp.float32)
          for _ in range(requests)]
    points = []
    for n in sorted(replica_counts):
        fleet = ReplicaFleet({model: prefix}, n=n, backend=backend,
                             warmup=False, probe_ms=60000.0,
                             buckets=[1, 2, 4]).spawn()
        router = FleetRouter(fleet)
        try:
            def call(i):
                out, _t = router.route(model, (xs[i % requests],),
                                       deadline_ms=10000.0)
                return out

            # calibration: closed-loop ceiling for THIS replica count
            sync_volley(call, min(16, requests), clients=clients)
            cal = sync_volley(call, requests, clients=clients)
            if cal.errors:
                raise RuntimeError(
                    f"calibration volley failed at n={n}: "
                    f"{cal.errors[0][1]!r}")
            for frac in sorted(load_fractions):
                offered = max(cal.rps * frac, 0.5)
                probe = open_loop(call, offered, requests)
                conformant = (probe["errors"] == 0
                              and probe["completed"] >= 0.9 * requests
                              and probe["achieved_rps"]
                              >= 0.8 * offered
                              and (target_ms is None
                                   or probe["p99_ms"] <= target_ms))
                points.append({
                    "replicas": n,
                    "load_fraction": frac,
                    "offered_rps": round(offered, 2),
                    "achieved_rps": round(probe["achieved_rps"], 2),
                    "p99_ms": round(probe["p99_ms"], 3),
                    "errors": probe["errors"],
                    "conformant": bool(conformant),
                })
        finally:
            router.shutdown()
    return {"points": points, "knee": find_knee(points),
            "slo_class": slo_class, "target_ms": target_ms,
            "requests_per_point": requests}


def find_knee(points) -> dict:
    """Reduce sweep points to per-replica-count SLO capacity and the
    knee: the last replica count whose marginal capacity gain still
    reaches half the first count's per-replica capacity (past it,
    adding replicas stops paying — the planning answer a capacity
    curve exists to give)."""
    caps: dict = {}
    for pt in points:
        if pt["conformant"]:
            caps[pt["replicas"]] = max(caps.get(pt["replicas"], 0.0),
                                       pt["offered_rps"])
    counts = sorted(caps)
    if not counts:
        return {"capacity_rps": {}, "knee_replicas": None,
                "per_replica_rps": None}
    base = caps[counts[0]] / counts[0]
    knee = counts[0]
    for prev, cur in zip(counts, counts[1:]):
        marginal = (caps[cur] - caps[prev]) / (cur - prev)
        if marginal >= 0.5 * base:
            knee = cur
        else:
            break
    return {"capacity_rps": {str(c): round(caps[c], 2)
                             for c in counts},
            "knee_replicas": knee,
            "per_replica_rps": round(base, 2)}
