"""Replica fleet: N inference replicas behind one lifecycle manager.

The single-process server (PR 3) dies whole: one crash, one stuck
compile, one reload takes 100% of traffic down.  This module is the
replica layer under the fleet router (:mod:`.router`): it spawns or
adopts N replicas of the same model set, tracks each through an
explicit state machine, probes their health, and walks them one at a
time through zero-downtime rolling reloads.

State machine (per replica)::

    starting ──► warming ──► ready ◄──► draining
        │            │         │            │
        └────────────┴────┬────┴────────────┘
                          ▼
                        dead

* ``starting``  constructed, worker not yet loading
* ``warming``   models loading + per-bucket warmup compiling
* ``ready``     serving; routable iff also probe-``healthy``
* ``draining``  out of rotation (rolling reload / shutdown);
                in-flight requests finish
* ``dead``      killed or exited; never re-admitted

Two replica backends share one interface:

* :class:`ThreadReplica` — an in-process ``ModelRepository`` (its own
  predictors, batchers, compile caches).  Cheap to spawn, the default
  for tests and single-host fleets; a *kill* makes every subsequent
  call raise ``ConnectionResetError``, exactly what a crashed process
  looks like to the router.
* :class:`ProcessReplica` — a real ``python -m ...serving.server``
  subprocess on an ephemeral port, spoken to over HTTP.  True isolation
  (own GIL, own device client, killable with SIGKILL); the backend the
  scaling bench and production use.

Health is double-sourced: an **active prober** hits each ready
replica's ``/healthz`` every ``MXNET_SERVING_FLEET_PROBE_MS`` and
demands structured per-model ``ready`` state (a warming model is not
routable), while the router feeds **passive** per-request outcomes
into the same consecutive-failure budget
(``MXNET_SERVING_FLEET_PROBE_FAILS``).  One success from either source
re-admits.

Fault points: ``serving.probe`` fires before each active probe;
``serving.replica_exec`` fires as a replica accepts a routed request
(both docs/fault_tolerance.md).
"""
from __future__ import annotations

import json
import os
import queue as _queue
import subprocess
import sys
import threading
import time

import numpy as onp

from ..base import get_env
from .. import fault, flightrec, trace
from ..error import ReplicaUnavailableError
from ..locks import named_lock
from .admission import (BadRequest, DeadlineExceeded, ModelNotFound,
                        QueueFullError, ServingError, ShuttingDown)

__all__ = ["ReplicaFleet", "ThreadReplica", "ProcessReplica",
           "STARTING", "WARMING", "READY", "DRAINING", "DEAD"]

STARTING = "starting"
WARMING = "warming"
READY = "ready"
DRAINING = "draining"
DEAD = "dead"


class _ReplicaBase:
    """Shared lifecycle + health bookkeeping for both backends."""

    backend = "?"

    def __init__(self, rid, models, probe_fails=None):
        self.rid = rid
        self.models = dict(models)          # name -> artifact prefix
        self.state = STARTING
        self._killed = False
        self._healthy = True
        self._fails = 0                     # consecutive probe/request
        self._probe_fails = int(
            probe_fails if probe_fails is not None
            else get_env("MXNET_SERVING_FLEET_PROBE_FAILS", 3, int))
        self._inflight = 0
        self._lock = named_lock("fleet.replica")

    def _to(self, new_state):
        """One state-machine transition, recorded in the flight ring —
        the replica lifecycle IS the story a dead-fleet postmortem
        reconstructs.  No-op (and no event) when the state is already
        ``new_state``."""
        old = self.state
        if old == new_state:
            return
        self.state = new_state
        flightrec.record(flightrec.LIFECYCLE, "replica.state",
                         severity="warn" if new_state == DEAD
                         else "info",
                         replica=self.rid, frm=old, to=new_state)

    # -- routing view -------------------------------------------------

    @property
    def healthy(self):
        return self._healthy

    @property
    def inflight(self):
        return self._inflight

    def routable(self):
        return self.state == READY and self._healthy

    def track(self):
        """Context manager bumping the inflight gauge around one hop."""
        return _Inflight(self)

    # -- health accounting (active probe + passive request outcomes) --

    def note_success(self):
        with self._lock:
            self._fails = 0
            readmitted = not self._healthy
            self._healthy = True
        if readmitted:
            flightrec.record(flightrec.HEALTH, "replica.readmitted",
                             replica=self.rid)

    def note_failure(self):
        """One failed probe or failed routed request.  Returns True
        when this failure crossed the consecutive-failure budget and
        quarantined the replica."""
        with self._lock:
            self._fails += 1
            crossed = self._healthy and self._fails >= self._probe_fails
            if crossed:
                self._healthy = False
        if crossed:
            flightrec.record(flightrec.HEALTH, "replica.quarantined",
                             severity="warn", replica=self.rid,
                             fails=self._fails)
        return crossed

    # -- lifecycle ----------------------------------------------------

    def begin_drain(self):
        if self.state in (READY, WARMING, STARTING):
            self._to(DRAINING)

    def readmit(self):
        """Back into rotation after a drain (rolling reload step done).
        A dead replica stays dead."""
        if self.state == DRAINING and not self._killed:
            self._to(READY)
            self.note_success()

    def kill(self):
        """Simulate/perform a crash: the replica answers nothing ever
        again.  In-flight behaviour is backend-specific (a killed
        process resets its sockets; a killed thread replica lets
        already-executing batches finish — admission dies either way)."""
        self._killed = True
        self._to(DEAD)

    def has_model(self, name):
        """True when this replica serves ``name`` (multi-tenant
        routing filter: replicas no longer all hold the same set)."""
        return name in self.models

    def describe(self):
        return {"state": self.state, "healthy": self._healthy,
                "inflight": self._inflight, "backend": self.backend,
                "models": sorted(self.models)}

    # -- autoscaler signals (defaults; backends refine) ----------------

    def vitals(self):
        """One combined load probe: ``{"queues": {model: depth},
        "sessions": live-session-count, "streams": active-stream-
        count}``.  The autoscaler calls this ONCE per replica per
        tick — for a process replica it is a single ``/healthz``
        round trip, and splitting it per-signal would multiply the
        control loop's I/O.  A dead/unreachable replica reports
        empty."""
        return {"queues": {}, "sessions": 0, "streams": 0}

    def active_streams(self):
        """Streams currently riding this replica's decode loops —
        re-probed fresh each time the shrink path re-checks quiesce
        (a shrink only closes a replica once they reach a step
        boundary).  Queue depths and session counts ride the same
        :meth:`vitals` probe and have no separate accessor: the
        autoscaler consumes the combined sweep."""
        return self.vitals()["streams"]

    # -- interface the backends implement -----------------------------

    def start(self):
        raise NotImplementedError

    def predict(self, name, inputs, deadline_ms=None, inputs_json=None):
        raise NotImplementedError

    def healthz(self):
        raise NotImplementedError

    def admin(self, verb, name, path=None, version=None, warmup=None):
        raise NotImplementedError

    def model_meta(self, name):
        raise NotImplementedError

    def close(self, timeout=30.0):
        raise NotImplementedError

    # stateful sessions (docs/serving.md "Sessions"): the replica owns
    # the carry; the router owns which replica that is (affinity)

    def session_create(self, model, sid=None):
        raise NotImplementedError

    def session_step(self, model, sid, inputs, steps=1,
                     deadline_ms=None, on_chunk=None):
        raise NotImplementedError

    def session_close(self, model, sid):
        raise NotImplementedError

    def session_adopt(self, model, sid):
        raise NotImplementedError


class _Inflight:
    __slots__ = ("_r",)

    def __init__(self, replica):
        self._r = replica

    def __enter__(self):
        with self._r._lock:
            self._r._inflight += 1
        return self._r

    def __exit__(self, *exc):
        with self._r._lock:
            self._r._inflight -= 1
        return False


def _check_replica_exec(rid, name):
    """``serving.replica_exec`` fault hook: a transient fault here is a
    replica-side crash/stall the router's failover must absorb."""
    fault.inject("serving.replica_exec", f"{rid}:{name}")


class ThreadReplica(_ReplicaBase):
    """In-process replica: its own repository, predictors and batchers.

    No HTTP hop — the router calls straight into the repository.  Each
    replica still owns separate compile caches and queues, so fleet
    semantics (independent warmup, independent drain, per-replica
    load) are faithful; only the failure domain is shared."""

    backend = "thread"

    def __init__(self, rid, models, buckets=None, warmup=None,
                 probe_fails=None, session_models=None,
                 session_dir=None):
        super().__init__(rid, models, probe_fails=probe_fails)
        from .model_repository import ModelRepository
        from .sessions import SessionHost
        self.repository = ModelRepository(buckets=buckets)
        self.sessions = SessionHost(
            admission=self.repository.admission,
            snapshot_dir=session_dir, buckets=buckets)
        self._session_models = dict(session_models or {})
        self._warmup = warmup
        self._t_start = time.monotonic()

    def start(self):
        self._to(WARMING)
        try:
            for name, path in self.models.items():
                self.repository.load(name, path, warmup=self._warmup)
            for name, spec in self._session_models.items():
                self.sessions.add(
                    name, spec,
                    warmup=self._warmup is not False)
        except Exception:
            self._to(DEAD)
            raise
        if self.state == WARMING:   # a racing kill()/drain wins
            self._to(READY)
        return self

    def _gone(self):
        if self._killed:
            raise ConnectionResetError(
                f"replica {self.rid} is dead")

    def predict(self, name, inputs, deadline_ms=None, inputs_json=None):
        # in-process hop: typed arrays only — a JSON fallback would
        # lose the exported dtypes (json floats decode as f64)
        self._gone()
        _check_replica_exec(self.rid, name)
        with self.track():
            out, timing = self.repository.predict(name, inputs,
                                                  deadline_ms)
            import jax
            return jax.tree_util.tree_leaves(out), timing

    def healthz(self):
        self._gone()
        from .server import health_body
        return health_body(self.repository, self._t_start,
                           sessions=self.sessions)

    def session_create(self, model, sid=None):
        self._gone()
        return self.sessions.get(model).create(sid)

    def session_step(self, model, sid, inputs, steps=1,
                     deadline_ms=None, on_chunk=None):
        self._gone()
        _check_replica_exec(self.rid, f"{model}/{sid}")
        with self.track():
            mgr = self.sessions.get(model)
            if on_chunk is None:
                return mgr.step(sid, inputs, steps=steps,
                                deadline_ms=deadline_ms)
            handle = mgr.step(sid, inputs, steps=steps,
                              deadline_ms=deadline_ms, stream=True)
            budget_s = ((deadline_ms or 120000.0) / 1000.0 + 10.0)
            chunks = []
            try:
                while True:
                    try:
                        kind, payload = handle.chunk_queue.get(
                            timeout=budget_s)
                    except _queue.Empty:
                        raise DeadlineExceeded(
                            f"stream {model}/{sid} on replica "
                            f"{self.rid} stalled") from None
                    if kind == "chunk":
                        chunks.append(payload)
                        on_chunk(payload)
                    elif kind == "done":
                        return chunks, payload
                    else:
                        raise payload
            except BaseException:
                # covers a RAISING on_chunk relay too (client gone):
                # the decode loop must drop this stream at the next
                # boundary instead of decoding into the void
                handle.cancel()
                raise

    def session_close(self, model, sid):
        self._gone()
        return self.sessions.get(model).close(sid)

    def session_adopt(self, model, sid):
        self._gone()
        return self.sessions.get(model).restore(sid)

    def kill(self):
        """Crash simulation, session edition: the decode loops die
        with the "process" — active streams break typed at the next
        step boundary and NO parting snapshots are written (graceful
        snapshots are ``close()``'s job; a crash only has whatever
        the periodic snapshotter already made durable)."""
        super().kill()
        for name in self.sessions.names():
            self.sessions.get(name).batcher.drain(timeout=5.0)

    def admin(self, verb, name, path=None, version=None, warmup=None,
              slo=None):
        self._gone()
        if verb == "load":
            out = self.repository.load(name, path, version=version,
                                       warmup=warmup, slo=slo)
            self.models[name] = path
            return out
        if verb == "reload":
            out = self.repository.reload(name, path=path,
                                         version=version, warmup=warmup,
                                         slo=slo)
            if path is not None:
                self.models[name] = path
            return out
        if verb == "unload":
            out = self.repository.unload(name)
            self.models.pop(name, None)
            return out
        raise ValueError(f"unknown admin verb {verb!r}")

    def vitals(self):
        if self._killed:
            return {"queues": {}, "sessions": 0, "streams": 0}
        return {"queues": self.repository.queue_depths(),
                "sessions": self.sessions.active_sessions(),
                "streams": self.sessions.active_streams()}

    def model_meta(self, name):
        self._gone()
        return self.repository.get(name).predictor.meta["inputs"]

    def close(self, timeout=30.0):
        self._to(DEAD)
        self.repository.drain_all(timeout)
        # final sync snapshots: a post-drain migration is lossless
        self.sessions.drain_all(timeout)


class ProcessReplica(_ReplicaBase):
    """Subprocess replica: a real ``serving.server`` on an ephemeral
    port, isolated down to its own interpreter and device client."""

    backend = "process"

    def __init__(self, rid, models, warmup=None, probe_fails=None,
                 startup_timeout_s=300.0, session_models=None,
                 session_dir=None):
        super().__init__(rid, models, probe_fails=probe_fails)
        self._warmup = warmup
        self._session_models = dict(session_models or {})
        for name, spec in self._session_models.items():
            if not isinstance(spec, str):
                raise ValueError(
                    f"process replicas rebuild session models from "
                    f"registry spec strings; got {type(spec).__name__} "
                    f"for {name!r}")
        self._session_dir = session_dir
        self._startup_timeout_s = float(startup_timeout_s)
        self._proc = None
        self._port = None
        self._port_event = threading.Event()
        self._log_tail: list = []

    @property
    def port(self):
        return self._port

    def start(self):
        self._to(WARMING)
        cmd = [sys.executable, "-m",
               "incubator_mxnet_tpu.serving.server",
               "--host", "127.0.0.1", "--port", "0"]
        for name, path in self.models.items():
            cmd += ["--model", f"{name}={path}"]
        for name, spec in self._session_models.items():
            cmd += ["--session-model", f"{name}={spec}"]
        if self._session_dir is not None:
            cmd += ["--session-dir", str(self._session_dir)]
        if self._warmup is False:
            cmd.append("--no-warmup")
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        threading.Thread(target=self._read_stdout,
                         name=f"replica-{self.rid}-log",
                         daemon=True).start()
        if (not self._port_event.wait(self._startup_timeout_s)
                or self._port is None):
            # timed out, or the child exited before binding (the
            # stdout reader sets the event at EOF so a dead child
            # cannot hang the spawn — but it must not look READY)
            self.kill()
            raise ReplicaUnavailableError(
                f"replica {self.rid} did not come up within "
                f"{self._startup_timeout_s:.0f}s: "
                f"{' | '.join(self._log_tail[-5:])}")
        # server.main loads + warms every model BEFORE binding the
        # listener, so "listening" implies warm
        if self.state == WARMING:
            self._to(READY)
        return self

    def _read_stdout(self):
        for line in self._proc.stdout:
            line = line.rstrip()
            self._log_tail.append(line)
            del self._log_tail[:-50]
            if "] listening on " in line and not self._port_event.is_set():
                try:
                    self._port = int(line.rsplit(":", 1)[1])
                except ValueError:
                    continue
                self._port_event.set()
        self._port_event.set()   # EOF: unblock start() to report death

    def _gone(self):
        if self._killed or self._port is None:
            raise ConnectionResetError(f"replica {self.rid} is dead")
        if self._proc is not None and self._proc.poll() is not None:
            if self.state != DEAD:
                # an UNEXPECTED subprocess exit (vs kill()/close(),
                # which transition first) — the event a postmortem
                # anchors a replica death on
                flightrec.record(flightrec.LIFECYCLE, "replica.exited",
                                 severity="error", replica=self.rid,
                                 rc=self._proc.returncode)
            self._to(DEAD)
            raise ConnectionResetError(
                f"replica {self.rid} exited rc={self._proc.returncode}")

    def _http(self, method_path, body=None, timeout_s=30.0,
              headers=None):
        import http.client
        import urllib.error
        import urllib.request
        self._gone()
        method, path = method_path.split(" ", 1)
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        req = urllib.request.Request(
            f"http://127.0.0.1:{self._port}{path}", data=body,
            headers=hdrs, method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                status, raw = resp.status, resp.read()
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {"error": "HTTPError", "message": str(e)}
            return e.code, payload
        except (urllib.error.URLError, http.client.HTTPException,
                TimeoutError, OSError) as e:
            # ANY transport-level failure on the hop — refused socket,
            # reset or truncated mid-response (a SIGKILLed replica
            # raises IncompleteRead, an HTTPException, NOT a
            # ConnectionError), socket timeout — means this replica is
            # unavailable for this request; typed so the router fails
            # over instead of surfacing a 500
            raise ReplicaUnavailableError(
                f"replica {self.rid}: {type(e).__name__}: {e}") from e
        try:
            return status, json.loads(raw)
        except ValueError as e:
            raise ReplicaUnavailableError(
                f"replica {self.rid}: garbled response body: "
                f"{e}") from e

    @staticmethod
    def _raise_for(code, payload, rid, name):
        msg = f"replica {rid} [{name}]: {payload.get('message', payload)}"
        if code == 429:
            raise QueueFullError(msg)
        if code == 503:
            raise ShuttingDown(msg)
        if code == 504:
            raise DeadlineExceeded(msg,
                                   queue_ms=payload.get("queue_ms"),
                                   compute_ms=payload.get("compute_ms"))
        if code == 404:
            raise ModelNotFound(msg)
        if code == 400:
            raise BadRequest(msg)
        raise ServingError(msg)

    def predict(self, name, inputs, deadline_ms=None, inputs_json=None):
        _check_replica_exec(self.rid, name)
        if inputs_json is None:
            inputs_json = json.dumps(
                [onp.asarray(x).tolist() for x in inputs])
        body = ('{"inputs": %s%s}' % (
            inputs_json,
            f', "timeout_ms": {float(deadline_ms)}' if deadline_ms
            else "")).encode()
        # socket budget trails the request deadline slightly so the
        # server's typed 504 (with its queue/compute split) beats the
        # socket timeout
        timeout_s = (deadline_ms / 1000.0 + 2.0 if deadline_ms
                     else 120.0)
        # propagate the active trace across the process hop: the hop
        # span's id becomes the replica-side parent, so one timeline
        # covers router AND replica (a replica that predates the
        # header just ignores it — single-process trace)
        hval = trace.header_value(trace.current_span())
        with self.track():
            code, payload = self._http(
                f"POST /v1/models/{name}:predict", body, timeout_s,
                headers={trace.HEADER: hval} if hval else None)
        if code != 200:
            self._raise_for(code, payload, self.rid, name)
        return payload["outputs"], payload.get("timing", {})

    def healthz(self):
        return self._http("GET /healthz", timeout_s=10.0)

    def admin(self, verb, name, path=None, version=None, warmup=None,
              slo=None):
        body = {}
        if path is not None:
            body["path"] = path
        if version is not None:
            body["version"] = version
        if warmup is not None:
            body["warmup"] = warmup
        if slo is not None:
            body["slo"] = getattr(slo, "name", slo)
        code, payload = self._http(
            f"POST /v1/models/{name}:{verb}",
            json.dumps(body).encode(), timeout_s=600.0)
        if code != 200:
            self._raise_for(code, payload, self.rid, name)
        if verb == "load" or (verb == "reload" and path is not None):
            self.models[name] = path
        elif verb == "unload":
            self.models.pop(name, None)
        return payload

    def vitals(self):
        empty = {"queues": {}, "sessions": 0, "streams": 0}
        try:
            code, body = self.healthz()
        except (ConnectionError, ServingError):
            return empty
        if code not in (200, 503) or not isinstance(body, dict):
            return empty
        sessions = (body.get("sessions") or {}).values()
        return {
            "queues": {name: int(m.get("queue_depth") or 0)
                       for name, m in (body.get("models")
                                       or {}).items()},
            "sessions": sum(int(s.get("active_sessions") or 0)
                            for s in sessions),
            "streams": sum(int(s.get("active_streams") or 0)
                           for s in sessions),
        }

    def model_meta(self, name):
        code, payload = self._http("GET /v1/models", timeout_s=30.0)
        if code != 200:
            self._raise_for(code, payload, self.rid, name)
        if name not in payload.get("models", {}):
            raise ModelNotFound(f"model {name!r} not on replica "
                                f"{self.rid}")
        return payload["models"][name]["inputs"]

    # -- sessions over the wire ---------------------------------------

    @classmethod
    def _raise_session(cls, code, payload, rid, what):
        """Session errors carry their type in-band; 410 resolves back
        to the typed eviction/loss error the contract names."""
        from ..error import SessionExpiredError, SessionLostError
        err = payload.get("error")
        msg = (f"replica {rid} [{what}]: "
               f"{payload.get('message', payload)}")
        if err == "SessionLostError":
            raise SessionLostError(msg)
        if err == "SessionExpiredError" or code == 410:
            raise SessionExpiredError(msg)
        # in-band stream errors arrive under HTTP 200: resolve the
        # typed class by name, not status
        by_name = {"DeadlineExceeded": DeadlineExceeded,
                   "ShuttingDown": ShuttingDown,
                   "QueueFullError": QueueFullError,
                   "BadRequest": BadRequest,
                   "ModelNotFound": ModelNotFound,
                   "SessionNotFound": ModelNotFound}.get(err)
        if by_name is not None and code == 200:
            raise by_name(msg)
        cls._raise_for(code, payload, rid, what)

    def session_create(self, model, sid=None):
        body = {"session_id": sid} if sid else {}
        code, payload = self._http(
            f"POST /v1/sessions/{model}:create",
            json.dumps(body).encode(), timeout_s=60.0)
        if code != 200:
            self._raise_session(code, payload, self.rid, model)
        return payload

    def session_step(self, model, sid, inputs, steps=1,
                     deadline_ms=None, on_chunk=None):
        _check_replica_exec(self.rid, f"{model}/{sid}")
        body = {"inputs": [onp.asarray(x).tolist() for x in inputs],
                "steps": int(steps)}
        if deadline_ms:
            body["timeout_ms"] = float(deadline_ms)
        timeout_s = (deadline_ms / 1000.0 + 5.0 if deadline_ms
                     else 120.0)
        hval = trace.header_value(trace.current_span())
        with self.track():
            if on_chunk is None:
                code, payload = self._http(
                    f"POST /v1/sessions/{model}/{sid}:step",
                    json.dumps(body).encode(), timeout_s,
                    headers={trace.HEADER: hval} if hval else None)
                if code != 200:
                    self._raise_session(code, payload, self.rid,
                                        f"{model}/{sid}")
                return payload["outputs"], payload.get("timing", {})
            return self._session_stream(model, sid, body, timeout_s,
                                        on_chunk)

    def _session_stream(self, model, sid, body, timeout_s, on_chunk):
        """Streamed hop: relay each chunked JSON line as it arrives.
        A mid-stream transport loss (SIGKILLed replica) surfaces typed
        ``ReplicaUnavailableError`` — with chunks already delivered the
        router must NOT transparently re-run the stream (chunks cannot
        be unsent); the session itself recovers on the next step."""
        import http.client
        import urllib.error
        import urllib.request
        self._gone()
        body = dict(body)
        body["stream"] = True
        hdrs = {"Content-Type": "application/json"}
        hval = trace.header_value(trace.current_span())
        if hval:
            hdrs[trace.HEADER] = hval
        req = urllib.request.Request(
            f"http://127.0.0.1:{self._port}/v1/sessions/{model}/"
            f"{sid}:step", data=json.dumps(body).encode(),
            headers=hdrs)
        chunks = []
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                for line in resp:
                    msg = json.loads(line)
                    if "outputs" in msg:
                        chunks.append(msg["outputs"])
                        on_chunk(msg["outputs"])
                    elif "error" in msg:
                        self._raise_session(
                            200, msg, self.rid, f"{model}/{sid}")
                    else:
                        return chunks, msg.get("timing", {})
            raise ReplicaUnavailableError(
                f"replica {self.rid}: stream for {model}/{sid} ended "
                "without a done line")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {"error": "HTTPError", "message": str(e)}
            self._raise_session(e.code, payload, self.rid,
                                f"{model}/{sid}")
        except (urllib.error.URLError, http.client.HTTPException,
                TimeoutError, ValueError, OSError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.rid}: stream for {model}/{sid} broke "
                f"after {len(chunks)} chunk(s): "
                f"{type(e).__name__}: {e}") from e

    def session_close(self, model, sid):
        code, payload = self._http(
            f"POST /v1/sessions/{model}/{sid}:close", b"{}",
            timeout_s=60.0)
        if code != 200:
            self._raise_session(code, payload, self.rid,
                                f"{model}/{sid}")
        return payload

    def session_adopt(self, model, sid):
        code, payload = self._http(
            f"POST /v1/sessions/{model}/{sid}:adopt", b"{}",
            timeout_s=120.0)
        if code != 200:
            self._raise_session(code, payload, self.rid,
                                f"{model}/{sid}")
        return payload

    def kill(self):
        super().kill()
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()

    def close(self, timeout=30.0):
        self._to(DEAD)
        self._killed = True
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(10.0)


class ReplicaFleet:
    """Spawn/adopt N replicas; own their lifecycle, health and rolls.

    ``models`` maps model name -> artifact prefix; every replica loads
    the same set.  ``spawn()`` brings all replicas up concurrently and
    starts the active prober.  The router consumes :meth:`pick`
    (least-loaded routable replica) and :meth:`states` (gauges)."""

    def __init__(self, models, n=None, backend="thread", buckets=None,
                 warmup=None, probe_ms=None, probe_fails=None,
                 metrics=None, session_models=None, session_dir=None):
        self.models = dict(models)
        # name -> registry spec string; every replica hosts the same
        # session models, snapshotting into the SHARED session_dir so
        # any survivor can adopt a dead replica's sessions
        self.session_models = dict(session_models or {})
        self.session_dir = (
            session_dir if session_dir is not None
            else get_env("MXNET_SERVING_SESSION_DIR", None))
        self.n = int(n if n is not None
                     else get_env("MXNET_SERVING_FLEET_REPLICAS", 2, int))
        if self.n < 1:
            raise ValueError(f"fleet size must be >= 1, got {self.n}")
        if backend not in ("thread", "process"):
            raise ValueError(
                f"backend must be thread|process, got {backend!r}")
        self.backend = backend
        self.metrics = metrics            # FleetMetrics or None
        self._buckets = buckets
        self._warmup = warmup
        self._probe_ms = float(
            probe_ms if probe_ms is not None
            else get_env("MXNET_SERVING_FLEET_PROBE_MS", 500.0, float))
        self._probe_fails = probe_fails
        self._replicas: list = []
        self._next_rid = 0
        self._meta_cache: dict = {}       # name -> input specs
        self._lock = named_lock("fleet.state")
        self._stop = threading.Event()
        self._prober = None
        # the router-HA membership layer, when one is attached: this
        # fleet's summary() rides every lease beat, so every router in
        # the tier shares one view of every fleet (routerha.fleet_view)
        self.membership = None

    # -- shared membership view ---------------------------------------

    def attach_membership(self, membership):
        """Wire a :class:`~.routerha.RouterHA` to this fleet: the HA
        lease then publishes :meth:`summary` each beat, making this
        fleet part of the router tier's shared membership view."""
        self.membership = membership
        return self

    def summary(self):
        """Compact cross-router fleet view (published in the HA lease
        entry — small on purpose: it is re-written every beat)."""
        states = self.states()
        return {
            "backend": self.backend,
            "replicas": len(states),
            "ready": sum(1 for st in states.values()
                         if st["state"] == "ready" and st["healthy"]),
            "models": sorted(self.models),
            "session_models": sorted(self.session_models),
        }

    # -- lifecycle ----------------------------------------------------

    def _new_replica(self, models=None):
        with self._lock:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        models = self.models if models is None else models
        if self.backend == "process":
            return ProcessReplica(rid, models, warmup=self._warmup,
                                  probe_fails=self._probe_fails,
                                  session_models=self.session_models,
                                  session_dir=self.session_dir)
        return ThreadReplica(rid, models, buckets=self._buckets,
                             warmup=self._warmup,
                             probe_fails=self._probe_fails,
                             session_models=self.session_models,
                             session_dir=self.session_dir)

    def spawn(self):
        """Bring up all N replicas concurrently; raises if any failed
        to reach ``ready``.  Starts the prober.  Returns ``self``."""
        fresh = [self._new_replica() for _ in range(self.n)]
        with self._lock:
            self._replicas.extend(fresh)
        errors = []

        def up(r):
            try:
                r.start()
            except Exception as e:  # mxlint: allow-broad-except(collected and re-raised below — a failed replica must not strand the spawn barrier)
                errors.append((r.rid, e))

        threads = [threading.Thread(target=up, args=(r,),
                                    name=f"spawn-{r.rid}", daemon=True)
                   for r in fresh]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            self.shutdown()
            rid, e = errors[0]
            raise ReplicaUnavailableError(
                f"{len(errors)}/{self.n} replicas failed to start "
                f"(first: {rid}: {type(e).__name__}: {e})") from e
        self.start_prober()
        return self

    def spawn_one(self, models=None):
        """Bring up ONE additional replica (the autoscaler's grow
        verb), optionally with its own model subset — ``models=None``
        loads the fleet default set, ``{}`` spawns an empty replica
        the bin-packer then places models onto.  Blocks through load +
        warmup; a failed start leaves the replica out of the list and
        raises."""
        r = self._new_replica(models=None if models is None
                              else dict(models))
        try:
            r.start()
        except Exception as e:
            raise ReplicaUnavailableError(
                f"replica {r.rid} failed to start: "
                f"{type(e).__name__}: {e}") from e
        with self._lock:
            self._replicas.append(r)
        return r

    def remove(self, rid, timeout=30.0):
        """Drain + close one replica and drop it from the fleet (the
        autoscaler's shrink verb — the caller has already waited out
        sessions/in-flight work; ``close`` still snapshots whatever
        remains so a post-shrink migration is lossless)."""
        r = self.get(rid)
        r.begin_drain()
        try:
            r.close(timeout)
        finally:
            with self._lock:
                try:
                    self._replicas.remove(r)
                except ValueError:
                    pass
        return r

    def adopt(self, replica):
        """Take ownership of an externally-built replica (custom
        backend, pre-warmed process) — it is probed and routed like a
        spawned one."""
        with self._lock:
            self._replicas.append(replica)
        return replica

    def shutdown(self, timeout=30.0):
        self.stop_prober()
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            r.begin_drain()
        for r in replicas:
            try:
                r.close(timeout)
            except Exception:  # mxlint: allow-broad-except(best-effort teardown: one broken replica must not leak the rest)
                pass

    # -- routing view -------------------------------------------------

    @property
    def replicas(self):
        with self._lock:
            return list(self._replicas)

    def get(self, rid):
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid!r}")

    def routable(self, name=None):
        """Routable replicas; with ``name``, only those serving that
        model (multi-tenant packing means replicas differ)."""
        return [r for r in self.replicas
                if r.routable() and (name is None or r.has_model(name))]

    def ready_count(self):
        return len(self.routable())

    def all_draining(self):
        """True when every live replica is draining — the whole fleet
        is going away and new work must get 503 + Retry-After."""
        live = [r for r in self.replicas if r.state != DEAD]
        return bool(live) and all(r.state == DRAINING for r in live)

    def pick(self, exclude=frozenset(), name=None):
        """Least-loaded routable replica, preferring ones not in
        ``exclude`` (already-failed hops).  With ``name``, only
        replicas serving that model are candidates.  When every
        routable replica has been tried, fall back to the least-loaded
        one anyway — a transient double-fault on a 2-replica fleet
        should burn the remaining failover budget, not strand the
        request.

        Last resort: with nothing healthy, READY-but-quarantined
        replicas are still offered.  Quarantine demotes a replica
        below its healthy peers; it must not blackhole a fleet whose
        every survivor is mid-probe-window (a killed peer plus one
        unlucky probe burst used to 503 live requests for up to a
        probe interval).  A successful hop re-admits the replica
        (passive health note); a failed one costs what the immediate
        503 would have cost anyway."""
        candidates = self.routable(name)
        if not candidates:
            candidates = [r for r in self.replicas
                          if r.state == READY
                          and (name is None or r.has_model(name))]
        if not candidates:
            return None
        fresh = [r for r in candidates if r.rid not in exclude]
        pool = fresh or candidates
        return min(pool, key=lambda r: (r.inflight, r.rid))

    def states(self):
        """{rid: {state, healthy, inflight, backend}} — the gauges
        :class:`.metrics.FleetMetrics` exports."""
        return {r.rid: r.describe() for r in self.replicas}

    def kill(self, rid):
        """Chaos verb: hard-kill one replica (process: SIGKILL)."""
        self.get(rid).kill()

    def model_meta(self, name):
        """Input specs for ``name`` from any live replica (the router
        validates requests against these before routing).  Cached —
        for process replicas this is an HTTP hop, and it must not ride
        along on every predict; admin verbs and rolling reloads
        invalidate (a reload may point at a different artifact)."""
        cached = self._meta_cache.get(name)
        if cached is not None:
            return cached
        last = None
        claimants = [r for r in self.replicas
                     if r.state != DEAD and r.has_model(name)]
        if not claimants:
            # nobody is assigned the model.  On a classic fleet (every
            # replica loads self.models) that is an authoritative 404;
            # under autoscaling the router consults the control plane
            # (scale-from-zero) before surfacing it.
            raise ModelNotFound(f"model {name!r} not loaded on any "
                                "replica")
        for r in claimants:
            try:
                specs = r.model_meta(name)
                self._meta_cache[name] = specs
                return specs
            except ModelNotFound:
                if r.state == READY:
                    raise     # authoritative: a serving replica says no
                last = ModelNotFound(f"model {name!r} not loaded")
            except (ConnectionError, ServingError) as e:
                last = e
        raise ReplicaUnavailableError(
            f"no replica could describe model {name!r}") from last

    # -- fleet-wide admin ---------------------------------------------

    def load_everywhere(self, name, path, version=None, warmup=None,
                        slo=None):
        return self._admin_everywhere("load", name, path=path,
                                      version=version, warmup=warmup,
                                      slo=slo)

    def unload_everywhere(self, name):
        return self._admin_everywhere("unload", name)

    def _admin_everywhere(self, verb, name, **kw):
        # control-plane verbs get the same observability as requests
        # (PR 14 traced requests; admin verbs record into the flight
        # ring with their latency, so a slow :load is attributable)
        t0 = time.monotonic()
        out = {}
        try:
            for r in self.replicas:
                if r.state == DEAD:
                    continue
                out[r.rid] = r.admin(verb, name, **kw)
        except BaseException as e:
            flightrec.record(flightrec.SCALING, f"fleet.{verb}",
                             severity="error", model=name,
                             error=type(e).__name__,
                             replicas=len(out),
                             ms=round((time.monotonic() - t0) * 1e3, 3))
            raise
        self._meta_cache.pop(name, None)
        if verb == "load":
            self.models[name] = kw.get("path")
        elif verb == "unload":
            self.models.pop(name, None)
        flightrec.record(flightrec.SCALING, f"fleet.{verb}",
                         model=name, replicas=len(out),
                         ms=round((time.monotonic() - t0) * 1e3, 3))
        return out

    # -- zero-downtime rolling reload ---------------------------------

    def rolling_reload(self, name, path=None, version=None,
                       drain_timeout_s=30.0):
        """Reload ``name`` on every replica in rotation, one at a
        time: drain (out of rotation, in-flight finishes), reload (the
        repository's atomic swap + warmup), re-admit.  Ready capacity
        never drops below ``len(ready) - 1``; a reload failure
        re-admits the replica on its old version and surfaces, leaving
        a mixed-version fleet rather than a smaller one.

        "In rotation" means state READY including probe-quarantined
        replicas: quarantine is temporary, and a skipped unhealthy
        replica would re-admit itself later still serving the OLD
        version with nothing reporting the mixed fleet."""
        targets = [r for r in self.replicas if r.state == READY]
        if not targets:
            raise ReplicaUnavailableError(
                f"no replica in rotation to reload {name!r} on")
        self._meta_cache.pop(name, None)   # new version, new specs
        report = {"model": name, "replicas": [],
                  "min_ready": self.ready_count()}

        def note_ready():
            report["min_ready"] = min(report["min_ready"],
                                      self.ready_count())

        for r in targets:
            t0 = time.monotonic()
            r.begin_drain()
            note_ready()
            deadline = t0 + drain_timeout_s
            while r.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.002)
            try:
                info = r.admin("reload", name, path=path,
                               version=version)
            except BaseException as e:
                # old version still swapped in (the repository only
                # replaces after a successful build) — re-admit rather
                # than shrink the fleet
                flightrec.record(
                    flightrec.SCALING, "fleet.rolling_reload",
                    severity="error", model=name, replica=r.rid,
                    error=type(e).__name__)
                r.readmit()
                note_ready()
                raise
            r.readmit()
            note_ready()
            report["replicas"].append({
                "replica": r.rid,
                "version": info.get("version"),
                "ms": round((time.monotonic() - t0) * 1000.0, 3)})
        # a meta lookup that raced the roll may have cached the OLD
        # version's specs; drop it so the next one sees the new fleet
        self._meta_cache.pop(name, None)
        flightrec.record(
            flightrec.SCALING, "fleet.rolling_reload", model=name,
            replicas=len(report["replicas"]),
            min_ready=report["min_ready"],
            ms=round(sum(r["ms"] for r in report["replicas"]), 3))
        return report

    # -- active health probing ----------------------------------------

    def start_prober(self):
        if self._prober is not None and self._prober.is_alive():
            return
        self._stop.clear()
        self._prober = threading.Thread(target=self._probe_loop,
                                        name="fleet-prober",
                                        daemon=True)
        self._prober.start()

    def stop_prober(self):
        self._stop.set()
        if self._prober is not None:
            self._prober.join(5.0)
            self._prober = None

    def probe_once(self):
        """One active probe sweep (the prober loop body; callable
        directly from tests).  Only replicas in rotation are scored —
        warming and draining are lifecycle states, not health
        failures."""
        for r in self.replicas:
            if r.state not in (READY,):
                continue
            ok = False
            try:
                fault.inject("serving.probe", r.rid)
                code, body = r.healthz()
                models = body.get("models", {})
                # the contract is per-REPLICA: a multi-tenant replica
                # only owes the models packed onto it, not the fleet
                # union (on a classic fleet r.models == self.models)
                ok = (code == 200
                      and set(r.models) <= set(models)
                      and all(m.get("state") == "ready"
                              for m in models.values()))
            except Exception:  # mxlint: allow-broad-except(a probe that cannot complete IS the failure signal being counted)
                ok = False
            if ok:
                r.note_success()
            else:
                r.note_failure()
                if self.metrics is not None:
                    self.metrics.record_probe_failure(r.rid)

    def _probe_loop(self):
        while not self._stop.wait(self._probe_ms / 1000.0):
            self.probe_once()
