"""Multi-tenant placement: pack models onto replicas under an HBM
budget, evicting least-recently-used tenants when a load won't fit.

The fleet (PR 8) scales one model set out across N identical replicas;
production traffic is hundreds of models whose *sum* does not fit one
chip.  This module is the bin-packing half of the autoscaling control
plane (:mod:`.autoscaler` is the control-loop half): it keeps the
per-replica ledger of which model occupies how many bytes, answers
"where can this model go", and — when no replica has room — plans an
LRU eviction that frees exactly enough.

The budget currency is **memlint's export-time peak-HBM estimate**
(PR 9, ``analysis/memlint.py``): every exported artifact records its
forward's peak allocation in ``{prefix}.meta.json`` under
``memlint.peak_hbm_bytes``, which is the honest per-model bill — it
counts weights *and* the activation high-water mark of the largest
padded batch, not just parameter bytes.  Artifacts exported before the
memlint era fall back to ``MXNET_SERVING_MODEL_BYTES_DEFAULT``.

The placer is pure bookkeeping + decision math — it never touches a
replica.  The autoscaler applies its plans (and is the only writer),
which keeps every packing decision unit-testable without a fleet.
"""
from __future__ import annotations

import json
import threading

from ..base import get_env
from ..locks import named_lock

__all__ = ["Placer", "model_footprint_bytes"]


def model_footprint_bytes(path, default=None):
    """Peak-HBM bytes of the artifact at ``prefix`` ``path``, per chip.

    A mesh-sharded export (``export_model(sharding_rule=...)``) carries
    a per-shard plan in ``meta.json`` ``shardlint.
    peak_hbm_bytes_per_shard`` — each replica chip holds one shard, so
    THAT is its ledger charge.  Unsharded artifacts fall back to the
    whole-graph ``memlint.peak_hbm_bytes``, then to ``default`` /
    ``MXNET_SERVING_MODEL_BYTES_DEFAULT`` when the artifact predates
    the memlint era (or the plan was skipped at export)."""
    fallback = int(
        default if default is not None
        else get_env("MXNET_SERVING_MODEL_BYTES_DEFAULT",
                     64 * 1024 * 1024, int))
    try:
        with open(str(path) + ".meta.json") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return fallback
    per_shard = (meta.get("shardlint") or {}).get(
        "peak_hbm_bytes_per_shard")
    if per_shard and int(per_shard) > 0:
        return int(per_shard)
    peak = (meta.get("memlint") or {}).get("peak_hbm_bytes")
    if not peak or int(peak) <= 0:
        return fallback
    return int(peak)


class Placer:
    """Per-replica HBM ledger + packing decisions.

    ``budget_bytes`` caps the summed footprints of the models packed
    onto one replica (``MXNET_SERVING_REPLICA_HBM_BUDGET``; 0 =
    unlimited, the single-tenant default).  The ledger is written only
    through :meth:`record_load` / :meth:`record_unload` /
    :meth:`forget_replica`, which the autoscaler calls as it applies
    decisions — a planned-but-failed load never corrupts the books.
    """

    def __init__(self, budget_bytes=None):
        self.budget_bytes = int(
            budget_bytes if budget_bytes is not None
            else get_env("MXNET_SERVING_REPLICA_HBM_BUDGET", 0, int))
        self._lock = named_lock("placer.ledger")
        self._assigned: dict[str, dict[str, int]] = {}  # rid -> {m: b}

    # -- ledger --------------------------------------------------------

    def register_replica(self, rid):
        with self._lock:
            self._assigned.setdefault(rid, {})

    def forget_replica(self, rid):
        with self._lock:
            self._assigned.pop(rid, None)

    def record_load(self, rid, name, nbytes):
        with self._lock:
            self._assigned.setdefault(rid, {})[name] = int(nbytes)

    def record_unload(self, rid, name):
        with self._lock:
            models = self._assigned.get(rid)
            if models is not None:
                models.pop(name, None)

    # -- views ---------------------------------------------------------

    def replicas_of(self, name):
        """Replica ids currently holding ``name`` (the "actual" side
        of the desired-vs-actual gauge)."""
        with self._lock:
            return sorted(rid for rid, models in self._assigned.items()
                          if name in models)

    def models_on(self, rid):
        with self._lock:
            return dict(self._assigned.get(rid, {}))

    def used_bytes(self, rid):
        with self._lock:
            return sum(self._assigned.get(rid, {}).values())

    def free_bytes(self, rid):
        """Remaining budget on ``rid`` (``None`` = unlimited)."""
        if self.budget_bytes <= 0:
            return None
        return self.budget_bytes - self.used_bytes(rid)

    def assignments(self):
        with self._lock:
            return {rid: dict(models)
                    for rid, models in self._assigned.items()}

    # -- packing decisions ---------------------------------------------

    def choose(self, name, nbytes, candidates, idle_s_fn=None,
               protected=frozenset(), evict=True):
        """Pick where to load ``name`` (``nbytes`` footprint) among
        ``candidates`` (replica ids); returns ``(rid, evictions)``
        where ``evictions`` is the (possibly empty) list of model
        names to unload from ``rid`` first, in eviction order.

        Strategy: **best-fit** — the replica already fitting the model
        with the least free room left (keeps big holes for big
        models); if none fits and ``evict`` is allowed, the replica
        where evicting the fewest longest-idle tenants
        (``idle_s_fn(model) -> idle seconds``, LRU = largest idle
        first) frees enough.  Models in ``protected`` (e.g. the target
        itself, or pinned tenants) are never evicted.  Returns
        ``(None, [])`` when no candidate can make room — the caller's
        "spawn a new replica or fail typed" branch.  The autoscaler
        calls with ``evict=False`` first: spawning a fresh replica
        (when the fleet has headroom) always beats evicting a live
        tenant.
        """
        nbytes = int(nbytes)
        candidates = [rid for rid in candidates
                      if name not in self.models_on(rid)]
        if not candidates:
            return None, []
        if self.budget_bytes <= 0:
            # unlimited: pack onto the emptiest replica for balance
            return min(candidates,
                       key=lambda rid: (self.used_bytes(rid), rid)), []
        fits = [rid for rid in candidates
                if self.free_bytes(rid) >= nbytes]
        if fits:
            return min(fits,
                       key=lambda rid: (self.free_bytes(rid), rid)), []
        if not evict or nbytes > self.budget_bytes:
            return None, []     # no fit without eviction (or ever)
        idle_of = idle_s_fn or (lambda _m: 0.0)
        best = None             # (evict_count, -freed_idle, rid, plan)
        for rid in candidates:
            need = nbytes - self.free_bytes(rid)
            victims = sorted(
                ((m, b) for m, b in self.models_on(rid).items()
                 if m not in protected),
                key=lambda mb: -idle_of(mb[0]))   # most idle first
            plan, freed, idle_sum = [], 0, 0.0
            for m, b in victims:
                if freed >= need:
                    break
                plan.append(m)
                freed += b
                idle_sum += idle_of(m)
            if freed >= need:
                key = (len(plan), -idle_sum, rid)
                if best is None or key < best[0]:
                    best = (key, rid, plan)
        if best is None:
            return None, []
        return best[1], best[2]
