"""Highly-available router tier: leased membership, consistent-hash
session affinity, and crash takeover (docs/serving.md "Router high
availability").

The data plane already survives a replica SIGKILL (fleet failover +
session migration); this module removes the LAST single point of
failure — the router process itself.  N routers share one view of the
fleet and of session ownership through a small shared state store:

* **Leased membership** — every router publishes a lease entry
  (``join``), re-publishes it each beat (``renew``), and is considered
  dead once its deadline passes without a renewal.  The same
  join/heartbeat/expire shape as the PS-server elastic membership
  (``kvstore/ps_server.py``), with the same monotonic-deadline
  discipline: deadlines are ``time.monotonic()`` values, which Linux
  guarantees comparable across processes on one host (CLOCK_MONOTONIC
  is boot-wide) — exactly the scope of the file-backed store.  A beat
  that cannot land raises typed
  :class:`~..error.RouterLeaseError` (catchable as
  ``ConnectionError``; the next beat re-acquires).
* **Consistent-hash session affinity** — a :class:`HashRing` over the
  live members maps ``sid → owning router`` without any broadcast;
  the owning router's own affinity table maps ``sid → owning
  replica``.  Adding or removing a router moves only ~K/N session
  affinities (the ring test pins that bound).
* **Crash takeover** — when a router's lease expires, each survivor
  adopts the ring-share of the dead router's published sessions
  (``router.takeover.started`` / ``router.takeover.completed``
  MEMBERSHIP events) and resumes them through the existing
  snapshot-restore path: the replica-side ``session.restored`` re-base
  is visible in ``session_steps``, chunks already delivered are never
  re-sent — the PR 11 invariant, now across a *router* death.
* **Forward hop** — a session request landing on a non-owning router
  is forwarded to the owner with an ``X-MXNET-ROUTER`` hop header.
  Garbled or stale headers are ignored (never a 500 — the same
  discipline as ``X-MXNET-TRACE``); the hop budget
  (``MXNET_SERVING_ROUTER_FORWARD_HOPS``) turns a routing loop into
  typed :class:`~..error.RouterForwardError` instead of an infinite
  hop.

The store is pluggable: :class:`FileLeaseStore` (shared directory, one
atomically-renamed JSON file per router — no locks, no torn reads) for
cross-process fleets on one host, :class:`MemoryLeaseStore` for
in-process tests.  A PS-backed store only needs the same three
methods (``publish`` / ``read_all`` / ``remove``) over PSClient verbs.

Single-router deployments are bit-for-bit unaffected: with no
``MXNET_SERVING_ROUTER_HA_DIR`` (and no explicit ``RouterHA``), the
router starts no HA thread, publishes no lease, and its
``/healthz`` / ``describe()`` shapes stay exactly the pinned bare
ones — the ``"router_ha"`` block is additive, present only when HA is
configured.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time

from ..base import get_env
from .. import fault, flightrec
from ..error import RouterLeaseError
from ..locks import named_lock

__all__ = ["HEADER", "HashRing", "MemoryLeaseStore", "FileLeaseStore",
           "RouterHA", "parse_forward_header", "forward_header_value"]

#: Forward-hop header a router adds when relaying a mis-hashed session
#: request to its ring owner: ``"<hops>;<via,...>"``.  Parsed with
#: :func:`parse_forward_header`; anything garbled reads as hop 0.
HEADER = "X-MXNET-ROUTER"


def parse_forward_header(raw):
    """``"2;rA,rB"`` → ``(2, ("rA", "rB"))``.  Garbled, stale, or
    absent headers parse as ``(0, ())`` — a client-supplied (or
    corrupted) hop header must never 500 a request, it only loses its
    loop-accounting (the hop cap still bounds the loop)."""
    if not raw or not isinstance(raw, str) or len(raw) > 512:
        return 0, ()
    hops_part, _, via_part = raw.partition(";")
    try:
        hops = int(hops_part.strip())
    except (TypeError, ValueError):
        return 0, ()
    if hops < 0 or hops > 1024:
        return 0, ()
    via = tuple(v.strip() for v in via_part.split(",") if v.strip())
    return hops, via


def forward_header_value(hops, via):
    return f"{int(hops)};{','.join(via)}"


class HashRing:
    """Consistent-hash ring over router ids.

    Each member lands ``vnodes`` virtual points on a 160-bit circle
    (sha1 — stable across processes and Python runs, unlike
    ``hash()``); a key is owned by the first point clockwise from its
    own hash.  Removing a member re-homes ONLY the keys its points
    owned (~K/N of them); every other key keeps its owner — the
    stability bound the affinity tests pin."""

    def __init__(self, members, vnodes=64):
        self.members = tuple(sorted(set(members)))
        self.vnodes = int(vnodes)
        self._points = []
        for m in self.members:
            for v in range(self.vnodes):
                self._points.append((self._hash(f"{m}#{v}"), m))
        self._points.sort()
        self._keys = [p[0] for p in self._points]

    @staticmethod
    def _hash(key):
        return int.from_bytes(
            hashlib.sha1(str(key).encode()).digest()[:8], "big")

    def owner(self, key):
        """The member owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        h = self._hash(key)
        i = bisect.bisect_right(self._keys, h)
        if i == len(self._keys):
            i = 0
        return self._points[i][1]


# ---------------------------------------------------------------------------
# pluggable lease stores
# ---------------------------------------------------------------------------

class MemoryLeaseStore:
    """In-process store (tests, single-process multi-router rigs):
    a dict behind a lock, same contract as the file store."""

    def __init__(self):
        self._entries: dict = {}
        self._lock = named_lock("routerha.store")

    def publish(self, entry):
        with self._lock:
            self._entries[entry["router_id"]] = dict(entry)

    def read_all(self):
        with self._lock:
            return {rid: dict(e) for rid, e in self._entries.items()}

    def remove(self, router_id):
        with self._lock:
            self._entries.pop(router_id, None)


class FileLeaseStore:
    """Shared-directory store: one ``<router_id>.json`` per router,
    written atomically (tmp + rename), so readers never see a torn
    entry and writers never contend — there is no shared file and no
    lock.  Scoped to one host (monotonic deadlines are boot-wide, not
    cluster-wide); a cross-host fleet wants a PS-backed store with the
    same three methods."""

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, router_id):
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in str(router_id))
        return os.path.join(self.directory, f"{safe}.lease.json")

    def publish(self, entry):
        p = self._path(entry["router_id"])
        tmp = f"{p}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(entry, f)
            os.replace(tmp, p)   # atomic publish
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise RouterLeaseError(
                f"cannot publish lease for "
                f"{entry['router_id']!r} under {self.directory}: "
                f"{type(e).__name__}: {e}") from e

    def read_all(self):
        out = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".lease.json"):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    entry = json.load(f)
            except (OSError, ValueError):
                continue   # racing a writer's replace, or torn: skip
            rid = entry.get("router_id")
            if rid:
                out[rid] = entry
        return out

    def remove(self, router_id):
        try:
            os.unlink(self._path(router_id))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# the HA membership layer
# ---------------------------------------------------------------------------

class RouterHA:
    """Leased membership + consistent-hash affinity for one router.

    Attach to a :class:`~.router.FleetRouter` (``attach``), then either
    ``start()`` the beat/sweep thread (production) or drive
    ``beat_once()`` / ``sweep_once()`` by hand (tests — every state
    transition is reachable deterministically).  The lease entry a
    beat publishes carries everything the survivors need: the lease
    deadline, the router's HTTP address, its session registry
    (``sid → model``) and a compact summary of its replica fleet —
    the shared view of the fleet, one atomic read per peer."""

    def __init__(self, router_id, store, lease_ttl_s=None,
                 forward_hops=None, addr=None, vnodes=64):
        self.router_id = str(router_id)
        self.store = store
        self.lease_ttl_s = float(
            lease_ttl_s if lease_ttl_s is not None
            else get_env("MXNET_SERVING_ROUTER_LEASE_TTL_S", 3.0,
                         float))
        if self.lease_ttl_s <= 0:
            raise ValueError(
                f"lease TTL must be > 0, got {self.lease_ttl_s}")
        self.forward_hops = int(
            forward_hops if forward_hops is not None
            else get_env("MXNET_SERVING_ROUTER_FORWARD_HOPS", 3, int))
        self.addr = addr
        self.vnodes = int(vnodes)
        self.router = None
        self._epoch = 0
        self._joined = False
        self._announced_dead: set = set()
        self._taken_over: set = set()    # sids this router adopted
        self._counters = {"beats": 0, "beat_failures": 0,
                          "takeovers": 0, "adopted_sessions": 0,
                          "forwards": 0}
        self._lock = named_lock("routerha.member")
        self._stop = threading.Event()
        self._thread = None
        # the view refreshed by each sweep (store reads are cheap but
        # request-path lookups must not touch the store at all)
        self._view: dict = {}

    # -- wiring -------------------------------------------------------

    def attach(self, router):
        self.router = router
        router.ha = self
        if getattr(router, "fleet", None) is not None:
            router.fleet.attach_membership(self)
        return self

    def start(self):
        if self._thread is not None:
            return
        self.beat_once()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"router-ha-{self.router_id}",
            daemon=True)
        self._thread.start()

    def stop(self, leave=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.lease_ttl_s + 2.0)
            self._thread = None
        if leave and self._joined:
            self.store.remove(self.router_id)
            self._joined = False
            flightrec.record(flightrec.MEMBERSHIP, "router.exited",
                             router=self.router_id)

    def _loop(self):
        interval = self.lease_ttl_s / 3.0
        while not self._stop.wait(interval):
            try:
                self.beat_once()
            except RouterLeaseError:
                pass   # counted; the next beat re-acquires
            self.sweep_once()

    # -- lease beats --------------------------------------------------

    def _entry(self):
        sessions = {}
        fleet_summary = None
        if self.router is not None:
            with self.router._session_lock:
                sessions = {sid: mr[0] for sid, mr
                            in self.router._session_homes.items()}
            if getattr(self.router, "fleet", None) is not None:
                fleet_summary = self.router.fleet.summary()
        self._epoch += 1
        return {"router_id": self.router_id,
                "addr": self.addr,
                "deadline": time.monotonic() + self.lease_ttl_s,
                "ttl_s": self.lease_ttl_s,
                "epoch": self._epoch,
                "sessions": sessions,
                "fleet": fleet_summary}

    def beat_once(self):
        """Publish (join or renew) this router's lease.  A failed
        publish raises typed :class:`RouterLeaseError` — the lease
        simply ages; enough missed beats in a row and the survivors
        take over (exactly the PS heartbeat-budget semantics)."""
        try:
            fault.inject("serving.router_lease", self.router_id)
            entry = self._entry()
            self.store.publish(entry)
        except Exception as e:
            with self._lock:
                self._counters["beat_failures"] += 1
            flightrec.record(flightrec.MEMBERSHIP, "router.lease.beat_lost",
                             severity="warn", router=self.router_id,
                             error=type(e).__name__)
            if isinstance(e, RouterLeaseError):
                raise
            raise RouterLeaseError(
                f"router {self.router_id!r} lease beat failed: "
                f"{type(e).__name__}: {e}") from e
        with self._lock:
            self._counters["beats"] += 1
        if not self._joined:
            self._joined = True
            flightrec.record(flightrec.MEMBERSHIP,
                             "router.lease.acquired",
                             router=self.router_id, addr=self.addr,
                             ttl_s=self.lease_ttl_s)
        else:
            flightrec.record(flightrec.MEMBERSHIP,
                             "router.lease.renewed",
                             router=self.router_id,
                             epoch=entry["epoch"])
        return entry

    # -- membership view ----------------------------------------------

    def members(self, refresh=False):
        """{router_id: entry} of LIVE members (deadline not passed).
        Served from the last sweep's cached view unless ``refresh``."""
        if refresh or not self._view:
            self._view = self.store.read_all()
        now = time.monotonic()
        return {rid: e for rid, e in self._view.items()
                if float(e.get("deadline", 0)) > now}

    def expired(self, refresh=False):
        if refresh or not self._view:
            self._view = self.store.read_all()
        now = time.monotonic()
        return {rid: e for rid, e in self._view.items()
                if float(e.get("deadline", 0)) <= now
                and rid != self.router_id}

    def fleet_view(self):
        """The shared fleet view: every live router's published
        replica summary, one read per peer — no broadcast."""
        return {rid: e.get("fleet") for rid, e in
                self.members().items() if e.get("fleet") is not None}

    def ring(self):
        live = set(self.members())
        live.add(self.router_id)   # self is always a candidate owner
        return HashRing(live, vnodes=self.vnodes)

    def owner_of(self, sid):
        """``sid → owning router`` without a broadcast: a LIVE peer
        that published the sid in its session registry owns it
        (affinity survives ring changes); otherwise the consistent-
        hash ring decides."""
        members = self.members()
        if self.router is not None:
            with self.router._session_lock:
                if sid in self.router._session_homes:
                    return self.router_id
        for rid, e in members.items():
            if rid != self.router_id and sid in (e.get("sessions")
                                                 or {}):
                return rid
        return self.ring().owner(sid)

    def addr_of(self, rid):
        e = self.members().get(rid)
        return e.get("addr") if e else None

    def forward_target(self, sid):
        """None to handle locally, else ``(rid, addr)`` of the live
        owner to forward to.  A stale view naming an owner with no
        live lease (or no address) resolves to local handling — the
        takeover path will claim the sid here if the ring agrees."""
        owner = self.owner_of(sid)
        if owner is None or owner == self.router_id:
            return None
        addr = self.addr_of(owner)
        if not addr:
            return None
        return owner, addr

    # -- crash takeover -----------------------------------------------

    def sweep_once(self):
        """Refresh the membership view; adopt this router's ring-share
        of any expired peer's sessions.  Every survivor runs the same
        deterministic partition, so the dead router's affinities
        rehash across the survivors with no coordination and no double
        owner."""
        self._view = self.store.read_all()
        members = self.members()
        adopted = 0
        for rid, e in self.expired().items():
            if rid not in self._announced_dead:
                self._announced_dead.add(rid)
                flightrec.record(flightrec.MEMBERSHIP,
                                 "router.lease.expired",
                                 severity="warn", router=rid,
                                 ttl_s=e.get("ttl_s"),
                                 survivors=len(members))
            adopted += self._takeover(rid, e)
        # a rejoin (same id, fresh lease) clears the obituary so a
        # LATER death is announced again
        self._announced_dead -= set(members)
        # garbage-collect long-expired entries: every survivor has had
        # many sweeps to adopt its share by 10 lease TTLs
        now = time.monotonic()
        for rid, e in self.expired().items():
            if now - float(e.get("deadline", now)) > 10 * self.lease_ttl_s:
                self.store.remove(rid)
        return adopted

    def _takeover(self, dead_rid, entry):
        if self.router is None:
            return 0
        sessions = entry.get("sessions") or {}
        if not sessions:
            return 0
        ring = self.ring()
        with self.router._session_lock:
            mine = {sid: model for sid, model in sessions.items()
                    if ring.owner(sid) == self.router_id
                    and sid not in self.router._session_homes
                    and sid not in self._taken_over}
        if not mine:
            return 0
        flightrec.record(flightrec.MEMBERSHIP, "router.takeover.started",
                         severity="warn", router=self.router_id,
                         from_router=dead_rid, sessions=len(mine))
        for sid, model in mine.items():
            self.router._adopt_orphan(model, sid)
            self._taken_over.add(sid)
        with self._lock:
            self._counters["takeovers"] += 1
            self._counters["adopted_sessions"] += len(mine)
        # publish immediately so peers' owner_of() resolves to us
        # before our next periodic beat
        try:
            self.beat_once()
        except RouterLeaseError:
            pass
        flightrec.record(flightrec.MEMBERSHIP,
                         "router.takeover.completed",
                         router=self.router_id, from_router=dead_rid,
                         sessions=len(mine))
        return len(mine)

    def claim_orphan(self, sid):
        """Request-path takeover: a step for an unknown sid whose
        publisher's lease has expired.  Returns the model name when
        this router adopts it (ring-owner check included — a request
        mis-sent to a non-owner must not steal the sid), else None."""
        self._view = self.store.read_all()
        ring = self.ring()
        if ring.owner(sid) != self.router_id:
            return None
        for rid, e in self.expired().items():
            model = (e.get("sessions") or {}).get(sid)
            if model is not None:
                self.sweep_once()   # full takeover path: events + beat
                return model
        return None

    def note_forward(self):
        with self._lock:
            self._counters["forwards"] += 1

    # -- observability ------------------------------------------------

    def describe(self):
        """The additive ``"router_ha"`` healthz/describe block
        (docs/serving.md "Router high availability"); shape pinned by
        the routerha tests."""
        members = self.members()
        now = time.monotonic()
        self_entry = self._view.get(self.router_id)
        with self._lock:
            counters = dict(self._counters)
        return {
            "router_id": self.router_id,
            "addr": self.addr,
            "lease_ttl_s": self.lease_ttl_s,
            "forward_hops": self.forward_hops,
            "leased": self.router_id in members,
            "lease_remaining_s": (
                round(float(self_entry["deadline"]) - now, 3)
                if self_entry else None),
            "peers": {
                rid: {"addr": e.get("addr"),
                      "sessions": len(e.get("sessions") or {}),
                      "fleet": e.get("fleet")}
                for rid, e in members.items()
                if rid != self.router_id},
            "expired": sorted(self.expired()),
            "counters": counters,
        }


def from_env(host=None, port=None, router_id=None, ha_dir=None,
             lease_ttl_s=None, forward_hops=None):
    """Build a :class:`RouterHA` from the ``MXNET_SERVING_ROUTER_*``
    environment (returns None when ``MXNET_SERVING_ROUTER_HA_DIR`` is
    unset and no explicit ``ha_dir`` is given — HA stays fully off:
    no store, no thread, no lease traffic)."""
    ha_dir = ha_dir or get_env("MXNET_SERVING_ROUTER_HA_DIR", None)
    if not ha_dir:
        return None
    router_id = (router_id
                 or get_env("MXNET_SERVING_ROUTER_ID", None)
                 or f"router-{os.getpid()}")
    addr = f"{host}:{port}" if host and port else None
    return RouterHA(router_id, FileLeaseStore(ha_dir),
                    lease_ttl_s=lease_ttl_s,
                    forward_hops=forward_hops, addr=addr)
