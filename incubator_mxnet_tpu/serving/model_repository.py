"""Versioned model registry over ``deploy.load_predictor`` artifacts.

The reference framework's predict runtime loads one symbol+params pair
per process; a server needs a *repository*: several named models, each
with a live version, loadable/unloadable/reloadable while traffic
flows.  Three properties are load-bearing:

* **Warmup at load time** — ``warmup(bucket_sizes)`` pushes one zeros
  batch per padding bucket through the predictor, so every executable
  the batcher can request is compiled before the model is visible to
  traffic.  No user request ever pays a cold XLA compile (on TPU those
  are seconds, not microseconds).
* **Atomic reload** — the replacement version is fully loaded *and
  warmed* off to the side, then swapped in under the lock; the old
  version's batcher drains (in-flight requests finish on the weights
  they started with) and only then is it dropped.
* **Shared observability** — the repository feeds compile counts and
  queue depths to :class:`.metrics.ServingMetrics`, which is where the
  "compile count flatlines after warmup" invariant is scraped from.
"""
from __future__ import annotations

import contextlib
import threading

from ..base import get_env
from .. import trace
from ..locks import named_lock
from .admission import (Admission, ModelNotFound, ServingError,
                        checked_enqueue, slo_class)
from .batcher import DynamicBatcher, WeightedFairGate, parse_buckets

__all__ = ["ModelRepository", "ModelEntry"]


class ModelEntry:
    """One live (name, version) binding: predictor + its batcher."""

    __slots__ = ("name", "version", "path", "predictor", "batcher",
                 "cold_start_ms", "slo")

    def __init__(self, name, version, path, predictor, batcher,
                 slo=None):
        self.name = name
        self.version = version
        self.path = path
        self.predictor = predictor
        self.batcher = batcher
        self.cold_start_ms = None      # set once load + warmup finishes
        self.slo = slo_class(slo)      # SLO class (admission + WFQ)

    def describe(self):
        return {
            "version": self.version,
            "path": self.path,
            "slo": self.slo.name,
            "buckets": list(self.batcher.buckets),
            "max_batch": self.batcher.max_batch,
            "batch_polymorphic": self.predictor.batch_polymorphic,
            "cold_start_ms": self.cold_start_ms,
            "aot_buckets": self.predictor.aot_buckets,
            "aot_load_failures": self.predictor.aot_load_failures,
            "compile_count": self.predictor.compile_count,
            "queue_depth": self.batcher.depth,
            "inputs": self.predictor.meta["inputs"],
            "outputs": self.predictor.meta["outputs"],
            "graphlint_findings": (self.predictor.meta.get("graphlint")
                                   or {}).get("findings"),
            "memlint": self.memory_summary(),
        }

    def memory_summary(self):
        """Export-time memory plan (deploy._export_memlint): the
        per-model peak-HBM estimate and donation accounting the
        /metrics gauges report."""
        ml = self.predictor.meta.get("memlint") or {}
        return {
            "peak_hbm_bytes": ml.get("peak_hbm_bytes"),
            "donated_bytes_reclaimed": ml.get("donated_bytes_reclaimed"),
            "undonated_bytes": ml.get("undonated_bytes"),
            "donate_argnums": self.predictor.meta.get("donate_argnums"),
        }


class ModelRepository:
    def __init__(self, metrics=None, admission=None, buckets=None,
                 warmup=None):
        self.metrics = metrics
        self.admission = admission or Admission()
        self._buckets = (list(buckets) if buckets is not None
                         else parse_buckets())
        self._warmup_default = (
            warmup if warmup is not None
            else get_env("MXNET_SERVING_WARMUP", True, bool))
        self._models: dict[str, ModelEntry] = {}
        self._retired: list[ModelEntry] = []
        self._loading: dict[str, int] = {}   # name -> in-flight builds
        # one WFQ gate per repository: batches of co-packed models are
        # admitted to the device in SLO-weighted fair order
        self.exec_gate = WeightedFairGate()
        self._lock = named_lock("models.repository")
        if self.metrics is not None:
            self.metrics.attach_repository(self)

    def set_metrics(self, metrics):
        """Rebind the repository (and every live batcher) to a metrics
        instance — the server calls this when adopting a repository
        that was constructed without one, so batch counters don't
        silently vanish."""
        self.metrics = metrics
        with self._lock:
            entries = list(self._models.values()) + list(self._retired)
        for e in entries:
            e.batcher.metrics = metrics
        if metrics is not None:
            metrics.attach_repository(self)

    # -- build/teardown ----------------------------------------------

    @contextlib.contextmanager
    def _loading_state(self, name):
        """Track that ``name`` is being built (load + warmup): health
        probes report it as ``loading`` so a fleet prober / rolling
        reload can tell "warming, admit later" from "never heard of
        it".  Counted, not flagged — a reload racing a load must not
        clear the other's marker."""
        with self._lock:
            self._loading[name] = self._loading.get(name, 0) + 1
        try:
            yield
        finally:
            with self._lock:
                n = self._loading.get(name, 1) - 1
                if n <= 0:
                    self._loading.pop(name, None)
                else:
                    self._loading[name] = n

    def loading_names(self):
        """Names with a build (load or reload replacement) in flight."""
        with self._lock:
            return sorted(self._loading)

    def _build_entry(self, name, path, version, warmup, slo=None):
        import time
        from ..deploy import load_predictor
        slo = slo_class(slo)
        t0 = time.monotonic()
        # a load paid inside a request trace (scale-from-zero, cold
        # admin verbs) shows up as its own span — the cold-start cost
        # attributed to exactly the request that paid it
        with trace.span("model.load", model=name, version=version):
            predictor = load_predictor(path)
            # the artifact carries its export-time IR bill of health
            # (deploy._export_graphlint, docs/graph_analysis.md); the
            # deserialized executable is opaque to re-linting, so
            # surface the recorded findings at the serving boundary
            gl = predictor.meta.get("graphlint") or {}
            if gl.get("findings"):
                import warnings
                warnings.warn(
                    f"model {name!r} ({path}) exported with "
                    f"{gl['findings']} graphlint finding(s) "
                    f"{gl.get('by_rule')} — see its meta.json for "
                    "details")
            batcher = DynamicBatcher(name, predictor,
                                     metrics=self.metrics,
                                     buckets=self._buckets,
                                     exec_gate=self.exec_gate,
                                     weight=slo.weight)
            entry = ModelEntry(name, version, path, predictor, batcher,
                               slo=slo)
            do_warmup = (self._warmup_default if warmup is None
                         else warmup)
            if do_warmup:
                try:
                    self.warmup_entry(entry)
                except Exception:
                    # a failed warmup must not leak the worker thread
                    # (and through its closure the predictor's weights)
                    entry.batcher.drain()
                    raise
            # cold start = load (deserialize weights/graph + AOT
            # blobs) + warmup (executes every bucket); with a full AOT
            # bucket set this is deserialization, not compilation, and
            # compile_count at ready is 0 from process start
            entry.cold_start_ms = round(
                (time.monotonic() - t0) * 1000.0, 3)
        if self.metrics is not None:
            self.metrics.record_cold_start(
                name, entry.cold_start_ms,
                aot_loads=len(entry.predictor.aot_buckets),
                aot_load_failures=entry.predictor.aot_load_failures,
                compile_count=entry.predictor.compile_count)
        from .. import flightrec
        flightrec.record(flightrec.LIFECYCLE, "model.loaded",
                         model=name, version=version,
                         ms=entry.cold_start_ms,
                         compiles=entry.predictor.compile_count)
        return entry

    def warmup_entry(self, entry, bucket_sizes=None):
        if bucket_sizes is None:
            # the batcher's compile universe: every bucket a batch of
            # 1..max_batch requests can pad to.  That is the buckets
            # below the flush cap PLUS the bucket covering max_batch
            # itself — when the cap sits between buckets (max_batch=20,
            # buckets ...16,32) a 17..20-request batch pads to 32, which
            # must be warm too or the flatline invariant breaks
            b = entry.batcher
            sizes = sorted({s for s in b.buckets if s <= b.max_batch}
                           | {b._bucket_for(b.max_batch)})
        else:
            sizes = list(bucket_sizes)
        return entry.predictor.warmup(sizes)

    def load(self, name, path, version=None, warmup=None, slo=None):
        """Load a new model under ``name``; errors if it exists
        (``reload`` is the replace verb).  The entry only becomes
        visible after a successful load + warmup.  ``slo`` names the
        model's :class:`~.admission.SloClass` (admission shed order +
        WFQ weight); default ``standard``."""
        with self._loading_state(name):
            entry = self._build_entry(
                name, path, 1 if version is None else int(version),
                warmup, slo=slo)
        with self._lock:
            if name in self._models:
                entry.batcher.close()
                raise ServingError(
                    f"model {name!r} already loaded (v"
                    f"{self._models[name].version}); use reload")
            self._models[name] = entry
        return entry.describe()

    def reload(self, name, path=None, version=None, warmup=None,
               slo=None):
        """Atomic swap: build + warm the replacement, then swap the
        name binding; in-flight requests finish on the old version,
        whose batcher drains in the background.  ``slo`` defaults to
        the old version's class (a reload is not a policy change)."""
        with self._lock:
            old = self._models.get(name)
        if old is None:
            raise ModelNotFound(f"model {name!r} is not loaded")
        with self._loading_state(name):
            entry = self._build_entry(
                name, path or old.path,
                old.version + 1 if version is None else int(version),
                warmup, slo=slo if slo is not None else old.slo)
        with self._lock:
            old = self._models.get(name)   # re-read: racing reload/unload
            if old is not None:
                self._models[name] = entry
                self._retired.append(old)
        if old is None:
            # lost the race to an unload while building: tear down the
            # replacement (outside the lock — drain joins the worker)
            entry.batcher.drain()
            raise ModelNotFound(
                f"model {name!r} was unloaded during reload")
        threading.Thread(target=self._retire, args=(old,),
                         daemon=True).start()
        return entry.describe()

    def _retire(self, entry):
        entry.batcher.drain()
        with self._lock:
            try:
                self._retired.remove(entry)
            except ValueError:
                pass

    def unload(self, name):
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is None:
            raise ModelNotFound(f"model {name!r} is not loaded")
        entry.batcher.drain()
        self.exec_gate.forget(name)
        from .. import flightrec
        flightrec.record(flightrec.LIFECYCLE, "model.unloaded",
                         model=name, version=entry.version)
        return {"unloaded": name, "version": entry.version}

    def drain_all(self, timeout=30.0):
        """Graceful shutdown: stop admission, flush every queue."""
        self.admission.begin_drain()
        with self._lock:
            entries = list(self._models.values()) + list(self._retired)
        for e in entries:
            e.batcher.drain(timeout)

    # -- request path -------------------------------------------------

    def get(self, name):
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise ModelNotFound(f"model {name!r} is not loaded")
        return entry

    def has(self, name):
        with self._lock:
            return name in self._models

    def _submit_current(self, name, submit):
        """Resolve the live entry and run ``submit(entry)``, chasing a
        concurrent reload: between ``get`` and the batcher enqueue the
        name can be swapped to a new version and the OLD batcher begin
        draining — such a request is neither in-flight (it never
        enqueued) nor misaddressed (the model still serves), so it
        must land on the replacement, not die 503.  A genuine drain
        (server shutdown) or unload still surfaces typed."""
        from .admission import ShuttingDown
        entry = self.get(name)
        checked_enqueue(name)
        while True:
            try:
                return submit(entry)
            except ShuttingDown:
                if self.admission.draining:
                    raise              # whole-server drain: real 503
                fresh = self.get(name)  # unloaded -> ModelNotFound
                if fresh is entry:
                    raise              # draining for its own reasons
                entry = fresh          # reload swapped: retry on new

    def predict(self, name, inputs, deadline_ms=None):
        """Admission-gated batched predict; the server's hot path.
        The depth bound runs under the batcher's queue lock
        (``Admission.gate``) so concurrent arrivals cannot race past
        it; the ``serving.enqueue`` fault point fires outside the lock
        (an injected delay must not stall the flush worker)."""
        return self._submit_current(name, lambda entry:
            entry.batcher.submit(
                inputs, self.admission.deadline_ms(deadline_ms),
                admit=self.admission.gate(name, slo=entry.slo)))

    def predict_async(self, name, inputs, deadline_ms=None):
        """Admission-gated ``submit_async``: returns a
        :class:`~.batcher.PendingResult` so one caller thread can keep
        many single requests in flight."""
        return self._submit_current(name, lambda entry:
            entry.batcher.submit_async(
                inputs, self.admission.deadline_ms(deadline_ms),
                admit=self.admission.gate(name, slo=entry.slo)))

    # -- introspection ------------------------------------------------

    def models(self):
        with self._lock:
            entries = dict(self._models)
        return {name: e.describe() for name, e in entries.items()}

    def compile_counts(self):
        with self._lock:
            entries = dict(self._models)
        return {name: e.predictor.compile_count
                for name, e in entries.items()}

    def queue_depths(self):
        with self._lock:
            entries = dict(self._models)
        return {name: e.batcher.depth for name, e in entries.items()}

    def memory_summaries(self):
        """Per-model export-time memory plans for the /metrics gauges
        (peak-HBM estimate, donated-bytes-reclaimed)."""
        with self._lock:
            entries = dict(self._models)
        return {name: e.memory_summary() for name, e in entries.items()}
