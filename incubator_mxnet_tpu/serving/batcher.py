"""Per-model dynamic batcher: coalesce singles into padded buckets.

Concurrent single-instance requests against one model are stacked into
a batch, padded up to the next size in ``MXNET_SERVING_BATCH_BUCKETS``
(default ``1,2,4,8,16,32``), executed once, and sliced back out.  Two
triggers flush a forming batch, whichever fires first:

* **size** — ``MXNET_SERVING_MAX_BATCH`` requests are waiting, or
* **time** — the oldest waiting request has aged
  ``MXNET_SERVING_MAX_LATENCY_MS`` (partial-batch timer flush).

Requests are keyed by input *signature* (per-input instance shape +
dtype): only like-shaped requests share a batch, so the padded batch is
always rectangular.  On TPU the bucket set is the entire compile
universe — after ``ModelRepository`` warmup, every batch the batcher
can possibly emit replays an already-built executable.

Correctness contract (asserted in tests/test_serving.py): a response
sliced out of a padded batch is **bitwise identical** to the same
instance run unbatched, because row-independent inference math computes
each output row from its input row alone and XLA's reduction order
within a row does not depend on the number of rows.

``serving.execute`` is a fault-injection point; transient faults are
retried with :func:`fault.retry` backoff, permanent ones surface to
every request in the batch.
"""
from __future__ import annotations

import heapq
import queue as _queue
import threading
import time

import numpy as onp

from ..base import get_env
from .. import fault, trace
from ..locks import named_condition
from .admission import DeadlineExceeded, ServingError

__all__ = ["DynamicBatcher", "ContinuousBatcher", "PendingResult",
           "StreamResult", "WeightedFairGate", "parse_buckets"]


class WeightedFairGate:
    """Weighted fair queueing of device launches across the models of
    one replica (multi-tenant packing, docs/serving.md "Autoscaling").

    Each model's batcher owns its own worker thread; when several
    models share a replica those workers would otherwise contend for
    the device in OS-scheduler order, letting a chatty ``batch``-tier
    model starve an ``interactive`` one.  The gate serializes batch
    executions and admits them in virtual-finish-time order (classic
    WFQ): a batch of model *m* with weight *w* finishes at
    ``max(vtime, finish[m]) + cost/w``, and the pending batch with the
    smallest finish time runs next — so over any contended window each
    model gets device time proportional to its SLO weight, regardless
    of how many batches it queues.

    With a single model (or no contention) the gate degenerates to an
    uncontended lock acquire per batch."""

    def __init__(self):
        self._cond = named_condition("batcher.wfq")
        self._vtime = 0.0
        self._finish: dict[str, float] = {}   # per-key virtual finish
        self._heap: list = []                 # (finish, seq, key)
        self._seq = 0
        self._busy = False

    def acquire(self, key, weight=1.0, cost=1.0):
        """Block until it is ``key``'s turn; returns the token to hand
        :meth:`release`.  ``cost`` is the batch's nominal service
        demand (rows); ``weight`` the model's SLO share."""
        with self._cond:
            start = max(self._vtime, self._finish.get(key, 0.0))
            finish = start + float(cost) / max(float(weight), 1e-6)
            self._finish[key] = finish
            self._seq += 1
            ticket = (finish, self._seq, key)
            heapq.heappush(self._heap, ticket)
            while self._busy or self._heap[0] != ticket:
                self._cond.wait()
            heapq.heappop(self._heap)
            self._busy = True
        return finish

    def release(self, token):
        with self._cond:
            self._busy = False
            self._vtime = max(self._vtime, float(token))
            self._cond.notify_all()

    def forget(self, key):
        """Drop a retired model's virtual-time state (unload path)."""
        with self._cond:
            self._finish.pop(key, None)


def parse_buckets(text=None):
    """``MXNET_SERVING_BATCH_BUCKETS`` → sorted unique ints."""
    raw = (text if text is not None
           else get_env("MXNET_SERVING_BATCH_BUCKETS", "1,2,4,8,16,32"))
    try:
        sizes = sorted({int(v) for v in str(raw).split(",") if v.strip()})
    except ValueError:
        raise ValueError(
            f"MXNET_SERVING_BATCH_BUCKETS must be comma-separated ints, "
            f"got {raw!r}")
    if not sizes or sizes[0] < 1:
        raise ValueError(f"batch buckets must be >= 1, got {raw!r}")
    return sizes


class _Request:
    __slots__ = ("inputs", "event", "batch_out", "row", "error",
                 "t_enqueue", "deadline_ms", "queue_ms", "compute_ms",
                 "cancelled", "span")

    def __init__(self, inputs, deadline_ms):
        self.inputs = inputs
        self.event = threading.Event()
        self.batch_out = None    # whole-batch output pytree
        self.row = None          # this request's row in it
        self.error = None
        self.t_enqueue = time.monotonic()
        self.deadline_ms = deadline_ms
        self.queue_ms = None
        self.compute_ms = None
        self.cancelled = False
        # captured HERE (the caller's thread) because the flush worker
        # has no request context: the worker parents its queue/execute
        # spans on this.  None for unsampled requests — the usual case
        self.span = trace.current_span()

    def age_ms(self, now=None):
        return ((now if now is not None else time.monotonic())
                - self.t_enqueue) * 1000.0

    def expired(self, now=None):
        return (self.deadline_ms is not None
                and self.age_ms(now) > self.deadline_ms)


class PendingResult:
    """Handle for an in-flight request (``submit_async``)."""

    __slots__ = ("_batcher", "_req")

    def __init__(self, batcher, req):
        self._batcher = batcher
        self._req = req

    def cancel(self):
        """Withdraw this request: if its batch has not started
        executing yet, the flush worker drops it without spending
        device time (a hedged or failed-over request whose other copy
        already won, or a caller that stopped caring).  Best-effort —
        a request already riding an executing batch completes
        normally; ``result()`` after ``cancel()`` raises
        :class:`~.admission.DeadlineExceeded` once the worker has
        acknowledged the cancellation."""
        self._req.cancelled = True
        with self._batcher._cond:
            self._batcher._cond.notify()

    def result(self):
        """Block until this instance's slice of a batch is ready;
        returns ``(outputs, timing)``."""
        req = self._req
        # slack on top of the deadline: the worker stamps the 504 with
        # the queue/compute split; the local timeout is a backstop
        timeout = (None if req.deadline_ms is None
                   else req.deadline_ms / 1000.0 + 5.0)
        if not req.event.wait(timeout):
            req.cancelled = True
            raise DeadlineExceeded(
                f"request to {self._batcher.name!r} timed out awaiting "
                "batch", queue_ms=req.age_ms())
        if req.error is not None:
            raise req.error
        if req.cancelled and req.batch_out is None:
            # the worker acknowledged a cancel() before execution: no
            # result was ever produced for this row
            raise DeadlineExceeded(
                f"request to {self._batcher.name!r} was cancelled "
                "before execution", queue_ms=req.age_ms())
        # slice our row out here, on the caller's thread: the worker's
        # post-execute critical path stays O(1) per request
        out = req.batch_out
        if type(out) is onp.ndarray:       # single-output fast path
            result = out[req.row]
        else:
            import jax
            result = jax.tree_util.tree_map(
                lambda o, k=req.row: o[k], out)
        return result, {"queue_ms": req.queue_ms,
                        "compute_ms": req.compute_ms}


class DynamicBatcher:
    """One batching queue + worker thread per loaded model version.

    ``submit`` blocks the calling (HTTP handler) thread until its
    instance's slice of a batch is ready — callers never see batching,
    only lower tail latency under load.  ``submit_async`` returns a
    :class:`PendingResult` for callers multiplexing many in-flight
    requests on one thread.
    """

    def __init__(self, name, predictor, metrics=None, buckets=None,
                 max_batch=None, max_latency_ms=None, exec_gate=None,
                 weight=1.0):
        self.name = name
        self.predictor = predictor
        self.metrics = metrics
        # multi-tenant replicas share one WeightedFairGate across all
        # model batchers; weight comes from the model's SLO class
        self.exec_gate = exec_gate
        self.weight = float(weight)
        self.buckets = (list(buckets) if buckets is not None
                        else parse_buckets())
        self.max_batch = int(
            max_batch if max_batch is not None
            else get_env("MXNET_SERVING_MAX_BATCH", self.buckets[-1], int))
        if self.max_batch < 1:
            # 0 would make every group "full" while [:0] never drains
            # it — the worker would spin forever serving nothing
            raise ValueError(
                f"MXNET_SERVING_MAX_BATCH must be >= 1, got "
                f"{self.max_batch}")
        self.max_latency_ms = float(
            max_latency_ms if max_latency_ms is not None
            else get_env("MXNET_SERVING_MAX_LATENCY_MS", 5.0, float))
        if self.max_latency_ms < 0:
            raise ValueError(
                f"MXNET_SERVING_MAX_LATENCY_MS must be >= 0, got "
                f"{self.max_latency_ms}")
        self._retries = get_env("MXNET_SERVING_RETRIES", 3, int)
        self._pending: dict[tuple, list[_Request]] = {}
        self._depth = 0
        self._accepting = True
        self._running = True
        self._cond = named_condition("batcher.dynamic")
        self._worker = threading.Thread(
            target=self._loop, name=f"batcher-{name}", daemon=True)
        self._worker.start()

    # -- client side --------------------------------------------------

    @property
    def depth(self):
        """Queued-but-unfinished request count (admission + gauge)."""
        return self._depth  # mxlint: disable=MX-GUARD001(GIL-atomic int read used as an advisory gauge; the atomic admission bound runs under the lock via admit())

    def submit_async(self, inputs, deadline_ms=None, admit=None):
        """Enqueue one instance; returns a :class:`PendingResult` whose
        ``result()`` blocks.  Lets one client thread keep many single
        requests in flight (the shape an async HTTP front end has).

        ``inputs``: tuple of instance-level numpy arrays (the exported
        signature minus the leading batch dim).  ``admit`` is an
        optional ``callable(depth)`` (see ``Admission.gate``) run under
        the queue lock so its bound is atomic with the enqueue."""
        arrs = tuple(onp.asarray(x) for x in inputs)
        sig = tuple((a.shape, a.dtype) for a in arrs)
        req = _Request(arrs, deadline_ms)
        with self._cond:
            if not (self._accepting and self._running):
                from .admission import ShuttingDown
                raise ShuttingDown(
                    f"batcher for {self.name!r} is draining")
            if admit is not None:
                admit(self._depth)
            group = self._pending.setdefault(sig, [])
            group.append(req)
            self._depth += 1
            # wake the (sole) worker only when this submit changes what
            # it should do: a new group arms the flush timer, a full
            # group flushes now.  Intermediate submits would only make
            # the worker rescan and go back to sleep — under a 64-thread
            # burst that wake/rescan ping-pong dominates the wall clock.
            if len(group) == 1 or len(group) >= self.max_batch:
                self._cond.notify()
        return PendingResult(self, req)

    def submit(self, inputs, deadline_ms=None, admit=None):
        """Block until this instance's result is ready; returns
        ``(outputs, timing)`` — outputs is the instance-level output
        pytree, timing the queue/compute split in ms."""
        return self.submit_async(inputs, deadline_ms, admit).result()

    # -- worker side --------------------------------------------------

    def _take_batch(self):
        """Wait for a flushable signature group; pop up to max_batch of
        its requests.  Returns None only at shutdown."""
        with self._cond:
            while True:
                if not self._running and not self._pending:
                    return None
                now = time.monotonic()
                best_sig, best_age = None, -1.0
                for sig, reqs in self._pending.items():
                    if not reqs:
                        continue
                    age = reqs[0].age_ms(now)
                    full = len(reqs) >= self.max_batch
                    ripe = age >= self.max_latency_ms
                    # drain mode flushes immediately: no timer to wait out
                    if full or ripe or not self._running:
                        if age > best_age:
                            best_sig, best_age = sig, age
                if best_sig is not None:
                    reqs = self._pending[best_sig]
                    batch = reqs[:self.max_batch]
                    rest = reqs[self.max_batch:]
                    if rest:
                        self._pending[best_sig] = rest
                    else:
                        del self._pending[best_sig]
                    return batch
                # sleep until the oldest pending request ripens
                oldest = max((r[0].age_ms(now)
                              for r in self._pending.values() if r),
                             default=None)
                if oldest is None:
                    self._cond.wait()
                else:
                    self._cond.wait(
                        max(0.0, (self.max_latency_ms - oldest)) / 1000.0
                        + 0.0005)

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._execute(batch)
            finally:
                with self._cond:
                    self._depth -= len(batch)
                    self._cond.notify_all()

    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        # beyond the largest bucket the flush cap itself is the final
        # padding bucket: sizes in (buckets[-1], max_batch] must not
        # each compile their own executable (n never exceeds max_batch
        # — the worker slices batches to it)
        return self.max_batch

    def _execute(self, batch):
        t_start = time.monotonic()
        live = []
        for req in batch:
            if req.cancelled:
                # the caller withdrew (client disconnect, lost hedge
                # race): acknowledged here so the row never reaches
                # the device — counted, because dead requests that
                # STILL burn device time are the failure mode the
                # cancel wire exists to close
                if self.metrics is not None:
                    self.metrics.record_cancel(self.name)
                req.event.set()
            elif req.expired(t_start):
                req.queue_ms = req.age_ms(t_start)
                req.error = DeadlineExceeded(
                    f"request to {self.name!r} spent {req.queue_ms:.1f}ms "
                    "queued, past its deadline", queue_ms=req.queue_ms)
                req.event.set()
            else:
                live.append(req)
        if not live:
            return
        n = len(live)
        padded_to = self._bucket_for(n)
        # sampled riders get the queue-wait vs compute split as spans
        # (usually zero of them — the per-request cost is one attribute
        # test).  The execute span opens HERE so stack+pad cost is
        # inside it; injected serving.execute faults and retry events
        # attach to the oldest rider's span (the activated one).
        traced = [r for r in live if r.span is not None]
        for r in traced:
            trace.record_span("batch.queue", r.span, r.t_enqueue,
                              t_start, model=self.name)
        espans = [r.span.child("batch.execute", model=self.name,
                               rows=n, padded_to=padded_to)
                  for r in traced]
        try:
            stacked = tuple(
                onp.stack([r.inputs[i] for r in live])
                for i in range(len(live[0].inputs)))
            if padded_to > n:
                stacked = tuple(
                    onp.concatenate(
                        [s, onp.zeros((padded_to - n,) + s.shape[1:],
                                      s.dtype)])
                    for s in stacked)

            def run():
                # fault point + WFQ slot both live INSIDE the retry:
                # the gate is held only for the real device launch —
                # holding it across fault.retry's backoff sleeps would
                # stall every co-packed model behind one tenant's
                # transient faults (the priority inversion the gate
                # exists to prevent)
                fault.inject("serving.execute", self.name)
                token = (None if self.exec_gate is None
                         else self.exec_gate.acquire(
                             self.name, self.weight,
                             cost=float(padded_to)))
                try:
                    return self.predictor(*stacked)
                finally:
                    if token is not None:
                        self.exec_gate.release(token)

            t_exec = time.monotonic()
            with trace.activate(espans[0] if espans else None):
                out = fault.retry(run, max_attempts=self._retries,
                                  backoff=0.01, max_backoff=0.5)
            compute_ms = (time.monotonic() - t_exec) * 1000.0
            for es in espans:
                es.finish()
        except Exception as e:  # mxlint: allow-broad-except(wrapped as ServingError and delivered to every request in the batch)
            for es in espans:
                es.finish(outcome=type(e).__name__)
            err = e if isinstance(e, ServingError) else ServingError(
                f"batch execution failed for {self.name!r}: "
                f"{type(e).__name__}: {e}")
            for req in live:
                req.queue_ms = (t_start - req.t_enqueue) * 1000.0
                req.error = err
                req.event.set()
            return
        if self.metrics is not None:
            self.metrics.record_batch(self.name, n, padded_to)
        now = time.monotonic()
        for i, req in enumerate(live):
            req.queue_ms = (t_start - req.t_enqueue) * 1000.0
            req.compute_ms = compute_ms
            if req.expired(now):
                req.error = DeadlineExceeded(
                    f"request to {self.name!r} finished past its "
                    "deadline", queue_ms=req.queue_ms,
                    compute_ms=compute_ms)
            else:
                req.batch_out, req.row = out, i
            req.event.set()

    # -- lifecycle ----------------------------------------------------

    def drain(self, timeout=30.0):
        """Stop admitting, flush everything queued, stop the worker.
        In-flight requests finish normally — the atomic-reload path
        relies on this."""
        with self._cond:
            self._accepting = False
            self._running = False
            self._cond.notify_all()
        self._worker.join(timeout)
        return not self._worker.is_alive()

    close = drain


# ---------------------------------------------------------------------------
# continuous batching (stateful sessions)
# ---------------------------------------------------------------------------

class _Stream:
    """One session-step request: a *stream* of ``n_steps`` decode
    steps riding the running batch, one row per decode step."""

    __slots__ = ("sid", "inputs", "n_steps", "deadline_ms", "event",
                 "error", "chunks", "queue", "cancelled", "t_enqueue",
                 "t_admitted", "queue_ms", "compute_ms", "steps_done",
                 "carry", "checked_out", "session_steps", "span")

    def __init__(self, sid, inputs, n_steps, deadline_ms, stream):
        self.sid = sid
        self.inputs = inputs
        self.n_steps = int(n_steps)
        self.deadline_ms = deadline_ms
        self.event = threading.Event()
        self.error = None
        self.chunks = []           # per-step output leaf lists
        self.queue = _queue.SimpleQueue() if stream else None
        self.cancelled = False
        self.t_enqueue = time.monotonic()
        self.t_admitted = None
        self.queue_ms = None
        self.compute_ms = 0.0
        self.steps_done = 0
        self.carry = None          # checked-out carry row while active
        self.checked_out = False
        self.session_steps = None  # session-absolute count (owner's)
        # caller-thread trace context (same contract as _Request.span):
        # the decode worker parents its per-step spans on this
        self.span = trace.current_span()

    def age_ms(self, now=None):
        return ((now if now is not None else time.monotonic())
                - self.t_enqueue) * 1000.0

    def expired(self, now=None):
        return (self.deadline_ms is not None
                and self.age_ms(now) > self.deadline_ms)

    def timing(self):
        # session_steps is the session-ABSOLUTE count after this
        # stream's last step: a client that remembers it can detect a
        # migration's snapshot re-base (the count stepping backwards)
        # — the opposite of a silent restart
        return {"queue_ms": self.queue_ms, "compute_ms": self.compute_ms,
                "steps": self.steps_done,
                "session_steps": self.session_steps}


class StreamResult:
    """Handle for an in-flight session stream (continuous batching).

    ``result()`` blocks until every step ran and returns
    ``(chunks, timing)`` — chunks is the per-step list of output leaf
    arrays, whose concatenation is bitwise-identical to the
    non-streamed response.  With ``stream=True`` at submit, per-step
    chunks also arrive on :attr:`chunk_queue` as ``("chunk", leaves)``
    tuples terminated by ``("done", timing)`` or ``("error", exc)`` —
    the shape an HTTP chunked-response writer consumes."""

    __slots__ = ("_batcher", "_req")

    def __init__(self, batcher, req):
        self._batcher = batcher
        self._req = req

    @property
    def sid(self):
        return self._req.sid

    @property
    def chunk_queue(self):
        return self._req.queue

    @property
    def steps_done(self):
        return self._req.steps_done

    def cancel(self):
        """Withdraw the stream: the worker drops it at the next decode
        step boundary (the session keeps the carry of every step that
        already ran — a cancel is a truncation, never a corruption)."""
        self._req.cancelled = True
        with self._batcher._cond:
            self._batcher._cond.notify()

    def wait(self, timeout=None):
        return self._req.event.wait(timeout)

    def result(self):
        req = self._req
        timeout = (None if req.deadline_ms is None
                   else req.deadline_ms / 1000.0 + 10.0)
        if not req.event.wait(timeout):
            req.cancelled = True
            raise DeadlineExceeded(
                f"session stream on {self._batcher.name!r} timed out",
                queue_ms=req.age_ms())
        if req.error is not None:
            raise req.error
        if req.cancelled and req.steps_done < req.n_steps:
            raise DeadlineExceeded(
                f"session stream on {self._batcher.name!r} was "
                f"cancelled after {req.steps_done} step(s)",
                queue_ms=req.queue_ms)
        return list(req.chunks), req.timing()


class ContinuousBatcher:
    """Continuous-batching decode loop: streams join and leave a
    *running* batch between decode steps.

    Where :class:`DynamicBatcher` coalesces-then-flushes independent
    one-shot predicts, this worker owns a persistent set of *active*
    streams (one session each) and executes one batched decode step
    per iteration over their stacked carries.  Between any two steps,
    completed/cancelled/expired streams leave and queued streams join
    — admission is re-evaluated at every step boundary, so a new
    session starts decoding at the very next step, not after someone
    else's stream finishes.  Each step's batch is padded to the next
    size in ``buckets`` (the PR 10 AOT bucket set is the natural
    granularity), so the compile universe is closed after warmup:
    ``mxnet_serving_compile_total`` must stay flat across join/leave.

    The batcher is tree-agnostic: ``step_batch(carries, inputs,
    padded_to)`` (the session model's batched executor) does the
    stacking/padding/unstacking, and ``owner`` (the
    :class:`~.sessions.SessionManager`) supplies the carry lifecycle —
    ``checkout(sid)`` / ``writeback(sid, carry, step_ms)`` /
    ``release(sid)`` — so carries are owned by exactly one party at
    any instant and every write-back lands *between* decode steps
    (the crash-consistency point snapshots are taken at).

    ``serving.session_step`` fires per decode step; transient faults
    retry with ``fault.retry`` (``MXNET_SERVING_RETRIES``), permanent
    ones surface to every stream riding the step.
    """

    def __init__(self, name, step_batch, owner, buckets=None,
                 max_batch=None, metrics=None):
        self.name = name
        self.step_batch = step_batch
        self.owner = owner
        self.metrics = metrics
        self.buckets = (list(buckets) if buckets is not None
                        else parse_buckets())
        self.max_batch = int(
            max_batch if max_batch is not None
            else get_env("MXNET_SERVING_MAX_BATCH", self.buckets[-1],
                         int))
        if self.max_batch < 1:
            raise ValueError(
                f"MXNET_SERVING_MAX_BATCH must be >= 1, got "
                f"{self.max_batch}")
        self._retries = get_env("MXNET_SERVING_RETRIES", 3, int)
        self._pending: list[_Stream] = []
        self._active: list[_Stream] = []
        self._depth = 0
        self._running = True
        self._cond = named_condition("batcher.continuous")
        self._worker = threading.Thread(
            target=self._loop, name=f"continuous-{name}", daemon=True)
        self._worker.start()

    # -- client side --------------------------------------------------

    @property
    def depth(self):
        """Queued + active stream count (admission bound + gauge)."""
        return self._depth  # mxlint: disable=MX-GUARD001(GIL-atomic int read used as an advisory gauge; the atomic admission bound runs under the lock via admit())

    @property
    def active_streams(self):
        return len(self._active)  # mxlint: disable=MX-GUARD001(GIL-atomic len() of a list the worker swaps under its lock; advisory gauge only)

    def submit(self, sid, inputs, n_steps=1, deadline_ms=None,
               admit=None, stream=False):
        """Enqueue ``n_steps`` decode steps for session ``sid``;
        returns a :class:`StreamResult`.  ``admit`` runs under the
        queue lock (see ``Admission.gate``).  Steps of one session
        always run in submit order — a second stream for a session
        already decoding waits its turn."""
        req = _Stream(sid, tuple(inputs), n_steps, deadline_ms, stream)
        with self._cond:
            if not self._running:
                from .admission import ShuttingDown
                raise ShuttingDown(
                    f"session batcher for {self.name!r} is draining")
            if admit is not None:
                admit(self._depth)
            self._pending.append(req)
            self._depth += 1
            self._cond.notify()
        return StreamResult(self, req)

    # -- worker side --------------------------------------------------

    def _bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch

    def _finish(self, req, error=None, done=False):
        """Terminal transition of one stream; releases its session."""
        if req.checked_out:
            try:
                self.owner.release(req.sid)
            finally:
                req.checked_out = False
        req.error = error
        with self._cond:
            self._depth -= 1
        if req.queue is not None:
            req.queue.put(("error", error) if error is not None
                          else ("done", req.timing()))
        req.event.set()

    def _admit_locked(self, now):
        """Move pending streams into the active set (one per session,
        up to ``max_batch`` rows) — called under ``_cond`` at every
        step boundary, which is exactly what makes the batching
        *continuous*."""
        active_sids = {r.sid for r in self._active}
        still = []
        finished = []
        for req in self._pending:
            if req.cancelled:
                if self.metrics is not None:
                    self.metrics.record_cancel(self.name)
                finished.append((req, DeadlineExceeded(
                    f"stream for session {req.sid!r} cancelled while "
                    "queued", queue_ms=req.age_ms(now))))
                continue
            if req.expired(now):
                finished.append((req, DeadlineExceeded(
                    f"stream for session {req.sid!r} spent "
                    f"{req.age_ms(now):.1f}ms queued, past its "
                    "deadline", queue_ms=req.age_ms(now))))
                continue
            if (req.sid in active_sids
                    or len(self._active) >= self.max_batch):
                still.append(req)   # carry serialization / batch full
                continue
            try:
                req.carry = self.owner.checkout(req.sid)
                req.checked_out = True
            except Exception as e:  # mxlint: allow-broad-except(typed checkout failures — expired/lost/closed sessions — are delivered to the waiting stream)
                finished.append((req, e))
                continue
            req.t_admitted = now
            req.queue_ms = req.age_ms(now)
            self._active.append(req)
            active_sids.add(req.sid)
        self._pending = still
        return finished

    def _loop(self):
        while True:
            with self._cond:
                while (self._running and not self._pending
                       and not self._active):
                    self._cond.wait()
                if not self._running:
                    doomed = self._pending + self._active
                    self._pending, self._active = [], []
                else:
                    doomed = None
                    now = time.monotonic()
                    finished = self._admit_locked(now)
                    active = list(self._active)
            if doomed is not None:
                from .admission import ShuttingDown
                for req in doomed:
                    self._finish(req, ShuttingDown(
                        f"session batcher for {self.name!r} is "
                        "draining"))
                return
            for req, err in finished:
                self._finish(req, err)
            if active:
                self._decode_step(active)

    def _decode_step(self, active):
        now = time.monotonic()
        live = []
        left = []
        for req in active:
            if req.cancelled:
                if self.metrics is not None:
                    self.metrics.record_cancel(self.name)
                self._finish(req, DeadlineExceeded(
                    f"stream for session {req.sid!r} cancelled after "
                    f"{req.steps_done} step(s)", queue_ms=req.queue_ms))
                left.append(req)
            elif req.expired(now):
                self._finish(req, DeadlineExceeded(
                    f"stream for session {req.sid!r} passed its "
                    f"deadline after {req.steps_done} step(s)",
                    queue_ms=req.queue_ms, compute_ms=req.compute_ms))
                left.append(req)
            else:
                live.append(req)
        if live:
            t0 = time.monotonic()
            padded_to = self._bucket_for(len(live))
            # decode-step boundary spans for sampled streams: queue
            # wait recorded once (first step), then one span per step
            # so a stalled stream shows WHICH step stalled; injected
            # session_step faults attach to the oldest rider's span
            traced = [r for r in live if r.span is not None]
            for r in traced:
                if r.steps_done == 0 and r.t_admitted is not None:
                    trace.record_span("session.queue", r.span,
                                      r.t_enqueue, r.t_admitted,
                                      model=self.name, sid=r.sid)
            sspans = [r.span.child("session.decode_step",
                                   model=self.name, sid=r.sid,
                                   step=r.steps_done, rows=len(live),
                                   padded_to=padded_to)
                      for r in traced]
            try:
                def run():
                    fault.inject("serving.session_step", self.name)
                    return self.step_batch(
                        [r.carry for r in live],
                        [r.inputs for r in live], padded_to)
                with trace.activate(sspans[0] if sspans else None):
                    new_rows, out_rows = fault.retry(
                        run, max_attempts=self._retries, backoff=0.01,
                        max_backoff=0.5)
                for ss in sspans:
                    ss.finish()
            except Exception as e:  # mxlint: allow-broad-except(wrapped as ServingError and delivered to every stream riding the failed decode step)
                for ss in sspans:
                    ss.finish(outcome=type(e).__name__)
                err = e if isinstance(e, ServingError) else ServingError(
                    f"decode step failed for {self.name!r}: "
                    f"{type(e).__name__}: {e}")
                for req in live:
                    self._finish(req, err)
                    left.append(req)
                live = []
            if live:
                step_ms = (time.monotonic() - t0) * 1000.0
                if self.metrics is not None:
                    self.metrics.record_batch(self.name, len(live),
                                              padded_to)
                for i, req in enumerate(live):
                    req.carry = new_rows[i]
                    req.steps_done += 1
                    req.compute_ms += step_ms
                    try:
                        req.session_steps = self.owner.writeback(
                            req.sid, req.carry, step_ms)
                    except Exception as e:  # mxlint: allow-broad-except(a session closed/expired mid-stream surfaces typed on ITS stream; the other rows of the step are unaffected)
                        self._finish(req, e)
                        left.append(req)
                        continue
                    req.chunks.append(out_rows[i])
                    if req.queue is not None:
                        req.queue.put(("chunk", out_rows[i]))
                    if req.steps_done >= req.n_steps:
                        self._finish(req, done=True)
                        left.append(req)
        if left:
            with self._cond:
                self._active = [r for r in self._active
                                if r not in left]

    # -- lifecycle ----------------------------------------------------

    def drain(self, timeout=30.0):
        """Stop the decode loop: queued and active streams fail typed
        (``ShuttingDown``) at the next step boundary — the session
        carries they already produced stay written back, so a
        drain-then-migrate continuation loses nothing."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._worker.join(timeout)
        return not self._worker.is_alive()

    close = drain
