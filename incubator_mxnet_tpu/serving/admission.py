"""Admission control: backpressure, deadlines, graceful drain.

A server that batches perfectly but falls over under overload is not
production-shaped.  This module is the policy layer in front of the
batcher:

* **Bounded queues** — each model's pending queue holds at most
  ``MXNET_SERVING_QUEUE_DEPTH`` requests; beyond that the front end
  answers 429 immediately (fail fast beats queueing into timeout).
* **Deadlines** — every request carries one (client ``timeout_ms`` or
  ``MXNET_SERVING_DEADLINE_MS``).  A request that exceeds it answers
  504 carrying the queue-vs-compute time split, so the operator can
  tell "overloaded" (queue_ms dominates) from "model too slow"
  (compute_ms dominates).
* **Graceful drain** — shutdown stops admitting (503), lets in-flight
  batches finish, then joins the workers.

``fault.py`` integration: :func:`checked_enqueue` fires the
``serving.enqueue`` injection point and the batcher wraps device
execution in ``fault.retry`` around ``serving.execute``, so the
existing chaos machinery (ci/run_ci.py chaos stage grammar) exercises
the server's retry path like it does the kvstore's.
"""
from __future__ import annotations

import math

from ..base import get_env
from .. import fault

__all__ = ["ServingError", "QueueFullError", "DeadlineExceeded",
           "ShuttingDown", "ModelNotFound", "BadRequest",
           "ClientDisconnected", "Admission", "SloClass", "SLO_CLASSES",
           "slo_class", "checked_enqueue", "checked_route",
           "retry_after_s"]


class ServingError(Exception):
    """Base for serving-layer failures; carries the HTTP status."""
    http_status = 500

    def payload(self):
        return {"error": type(self).__name__, "message": str(self)}


class QueueFullError(ServingError):
    """Model queue at capacity — answer 429, client should back off."""
    http_status = 429


class DeadlineExceeded(ServingError):
    """Deadline elapsed; reports where the time went (queue vs compute)."""
    http_status = 504

    def __init__(self, msg, queue_ms=None, compute_ms=None):
        super().__init__(msg)
        self.queue_ms = queue_ms
        self.compute_ms = compute_ms

    def payload(self):
        out = super().payload()
        if self.queue_ms is not None:
            out["queue_ms"] = round(self.queue_ms, 3)
        if self.compute_ms is not None:
            out["compute_ms"] = round(self.compute_ms, 3)
        return out


class ShuttingDown(ServingError):
    """Server is draining — no new work admitted."""
    http_status = 503


class ModelNotFound(ServingError):
    http_status = 404


class BadRequest(ServingError):
    http_status = 400


class ClientDisconnected(ServingError):
    """The client hung up while its request was still queued (broken
    pipe / reset detected by the front end).  The request is cancelled
    so it stops consuming device time; no response is ever written —
    the 499 status (nginx convention) exists only for the metrics
    books."""
    http_status = 499


class SloClass:
    """One service-level class a model is served under.

    ``priority`` ranks the classes for the bin-packer's eviction
    protection (a strictly higher tier is never the LRU victim);
    ``weight`` is the share of device time the batcher's weighted-fair
    gate grants the model's batches when several models contend on one
    replica; ``shed_level`` drives overload admission — a class of
    shed level *k* is admitted only while the queue is below
    ``queue_depth * shed_fraction**k``.  ``shed_level`` is decoupled
    from ``priority`` on purpose: ``standard`` is the DEFAULT class of
    every model loaded without an explicit ``slo``, so it keeps the
    full pre-SLO queue bound (shed level 0) — only classes that opt
    into background economics (``batch``) shed early."""

    __slots__ = ("name", "priority", "weight", "shed_level")

    def __init__(self, name, priority, weight, shed_level=0):
        self.name = name
        self.priority = int(priority)
        self.weight = float(weight)
        self.shed_level = int(shed_level)

    def depth_bound(self, queue_depth, shed_fraction):
        """Effective queue bound for this class: the full depth scaled
        down ``shed_fraction`` per shed level below the top."""
        if self.shed_level <= 0:
            return queue_depth
        frac = (max(0.0, min(1.0, float(shed_fraction)))
                ** self.shed_level)
        return max(1, int(queue_depth * frac))

    def __repr__(self):
        return (f"SloClass({self.name!r}, priority={self.priority}, "
                f"weight={self.weight}, shed_level={self.shed_level})")


#: The built-in classes (autoscaler policies and ``:load`` bodies name
#: them by string).  ``interactive`` is the protected tier the
#: autoscale bench gates zero drops on; ``batch`` is shed first.
#: ``standard`` (the default) admits at the full queue bound, exactly
#: like a pre-SLO deployment.
SLO_CLASSES = {
    "interactive": SloClass("interactive", 0, 4.0, shed_level=0),
    "standard": SloClass("standard", 1, 2.0, shed_level=0),
    "batch": SloClass("batch", 2, 1.0, shed_level=1),
}


def slo_class(name):
    """Resolve a class name (or ``None`` / an :class:`SloClass`) to an
    :class:`SloClass`; unknown names raise ``BadRequest`` (they arrive
    from ``:load`` HTTP bodies)."""
    if name is None:
        return SLO_CLASSES["standard"]
    if isinstance(name, SloClass):
        return name
    cls = SLO_CLASSES.get(str(name))
    if cls is None:
        raise BadRequest(
            f"unknown SLO class {name!r} (known: "
            f"{', '.join(sorted(SLO_CLASSES))})")
    return cls


def retry_after_s(depth, service_ms=None, floor=1, cap=30):
    """Derive a ``Retry-After`` value (seconds, as the header string)
    from live state instead of a constant: roughly the time the
    current queue needs to flush — ``depth`` waiting requests times
    the observed per-request service time (p50 end-to-end; 50 ms
    until anything has been observed) — clamped to ``[floor, cap]``.
    A deeper queue tells clients to stay away longer; an idle drain
    tells them to come back almost immediately."""
    est = max(0, int(depth)) * (service_ms if service_ms else 50.0)
    return str(max(int(floor), min(int(cap), math.ceil(est / 1000.0))))


class Admission:
    """Per-server admission policy (shared by all models)."""

    def __init__(self, queue_depth=None, default_deadline_ms=None):
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else get_env("MXNET_SERVING_QUEUE_DEPTH", 256, int))
        self.default_deadline_ms = float(
            default_deadline_ms if default_deadline_ms is not None
            else get_env("MXNET_SERVING_DEADLINE_MS", 30000.0, float))
        self.shed_fraction = get_env(
            "MXNET_SERVING_SLO_SHED_FRACTION", 0.5, float)
        if not 0.0 < self.shed_fraction <= 1.0:
            raise ValueError(
                f"MXNET_SERVING_SLO_SHED_FRACTION must be in (0, 1], "
                f"got {self.shed_fraction}")
        self._draining = False

    @property
    def draining(self):
        return self._draining

    def begin_drain(self):
        self._draining = True

    def deadline_ms(self, requested=None):
        """Effective deadline: the client's ask, capped by the server
        default (a client cannot hold a slot longer than the operator
        allows)."""
        if requested is None:
            return self.default_deadline_ms
        return min(float(requested), self.default_deadline_ms)

    def admit(self, model_name, current_depth, slo=None):
        """Gate one request: drain state, then queue bound.  Raises the
        matching :class:`ServingError`; fires ``serving.enqueue``.
        One-shot form of :meth:`gate` for callers outside the batcher
        lock (the check is advisory there — see ``gate``)."""
        self.gate(model_name, slo=slo)(current_depth)
        checked_enqueue(model_name)

    def gate(self, model_name, slo=None):
        """Admission check as a callable the batcher runs **under its
        queue lock** (``submit_async(admit=...)``), making the depth
        bound atomic with the enqueue — a read-then-submit from here
        would let a burst of handler threads all pass the bound before
        any of them increments the depth.

        ``slo`` (an :class:`SloClass`) scales the depth bound down for
        lower-priority classes, so under overload they shed first: a
        ``batch`` request answers 429 while the queue still has
        headroom reserved for the ``interactive`` tier."""
        bound = (self.queue_depth if slo is None
                 else slo.depth_bound(self.queue_depth,
                                      self.shed_fraction))

        def check(current_depth):
            if self._draining:
                raise ShuttingDown(
                    "server is draining, not accepting work")
            if current_depth >= bound:
                tier = (f" ({slo.name} tier sheds at {bound})"
                        if slo is not None and bound < self.queue_depth
                        else "")
                raise QueueFullError(
                    f"model {model_name!r} queue full "
                    f"({current_depth}/{self.queue_depth}){tier}")
        return check


def checked_enqueue(model_name):
    """``serving.enqueue`` fault hook: a transient fault here models a
    lossy front-end hop and surfaces as 503 (retryable by the client);
    delays model admission latency."""
    fault.inject("serving.enqueue", model_name)


def checked_route(model_name):
    """``serving.route`` fault hook: the fleet router fires this before
    placing a request on a replica.  A transient fault models a lost
    routing hop (503 to the client, who may retry); a delay models a
    slow front end eating into the per-hop deadline budget."""
    fault.inject("serving.route", model_name)
