"""Checkpoint helpers + the FeedForward legacy API (reference
python/mxnet/model.py).

``save_checkpoint`` writes ``prefix-symbol.json`` (graph) +
``prefix-####.params`` (weights with ``arg:``/``aux:`` prefixes — the
reference's on-disk contract, model.py:189), ``load_checkpoint`` reads
them back.

``FeedForward`` is mxnet-1.x's oldest public training API (removed from
this fork's 2.0-era tree, but ported call sites still use it; VERDICT r3
Next #9).  It is a thin estimator facade over ``module.Module`` — the
same layering the reference used when it deprecated FeedForward in
favor of Module ("A module is like a FeedForward model",
module/__init__.py:18).
"""
from __future__ import annotations

import logging

from . import ndarray as nd
from . import symbol as sym_mod

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam", "FeedForward"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    """Load only the parameter dicts of a checkpoint."""
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:                       # unprefixed (gluon-style) entry
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class BatchEndParam:
    """Callback payload (reference model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_iter(X, y, batch_size, shuffle=False):
    """Classic FeedForward accepted numpy arrays or DataIters; normalize
    to a DataIter (reference model.py _init_iter semantics)."""
    from .io import NDArrayIter, DataIter
    if isinstance(X, DataIter):
        return X
    return NDArrayIter(X, y, batch_size=batch_size, shuffle=shuffle)


class FeedForward:
    """The mxnet-1.x estimator API: construct with a symbol, ``fit`` on
    data, ``predict``/``score``, ``save``/``load`` checkpoints.

    Implemented over :class:`incubator_mxnet_tpu.module.Module`; every
    method delegates to the Module training loop, so the compiled fused
    step, kvstore strategies, and metric registry are all the same code
    paths the modern APIs use.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, optimizer="sgd",
                 initializer=None, numpy_batch_size=128, arg_params=None,
                 aux_params=None, allow_extra_params=False, begin_epoch=0,
                 **optimizer_params):
        from . import initializer as _init
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.optimizer_params = optimizer_params or {"learning_rate": 0.01}
        self.initializer = initializer or _init.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._module = None

    # -- internals ---------------------------------------------------------

    def _label_names(self, data_iter):
        if getattr(data_iter, "provide_label", None):
            return [d.name for d in data_iter.provide_label]
        return ["softmax_label"]

    def _build_module(self, data_iter, for_training):
        from .module import Module
        ctx = self.ctx
        if ctx is not None and not isinstance(ctx, (list, tuple)):
            ctx = [ctx]
        mod = Module(self.symbol,
                     data_names=[d.name for d in data_iter.provide_data],
                     label_names=(self._label_names(data_iter)
                                  if for_training else None),
                     context=ctx)
        return mod

    # -- training ----------------------------------------------------------

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, monitor=None):
        train_data = _as_iter(X, y, self.numpy_batch_size, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = _as_iter(eval_data[0], eval_data[1],
                                 self.numpy_batch_size)
        assert self.num_epoch is not None, "please specify num_epoch"
        self._module = self._build_module(train_data, for_training=True)
        self._module.fit(
            train_data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=tuple(self.optimizer_params.items()),
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params,
            allow_missing=self.allow_extra_params,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
            monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    # -- inference ---------------------------------------------------------

    def _bound_for_predict(self, data_iter):
        mod = self._build_module(data_iter, for_training=False)
        mod.bind(data_shapes=data_iter.provide_data, label_shapes=None,
                 for_training=False)
        assert self.arg_params is not None, "call fit() or load() first"
        # allow_missing: loss-layer label inputs (e.g. softmax_label)
        # have no trained value and are unused by inference forward
        mod.init_params(arg_params=self.arg_params,
                        aux_params=self.aux_params,
                        allow_missing=True)
        missing = [k for k in mod.get_params()[0]
                   if k not in self.arg_params and "label" not in k]
        assert not missing, f"parameters without values: {missing}"
        return mod

    def predict(self, X, num_batch=None, return_data=False):
        """Run forward over the iterator; returns concatenated outputs
        (list when the net is multi-output, like the reference)."""
        import numpy as onp
        data_iter = _as_iter(X, None, self.numpy_batch_size)
        data_iter.reset()
        mod = self._bound_for_predict(data_iter)
        outputs, data_list, label_list = None, [], []
        for i, batch in enumerate(data_iter):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            outs = mod.get_outputs()
            n_valid = batch.data[0].shape[0] - getattr(batch, "pad", 0)
            outs = [o.asnumpy()[:n_valid] for o in outs]
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
            if return_data:
                data_list.append(batch.data[0].asnumpy()[:n_valid])
                if batch.label:
                    label_list.append(batch.label[0].asnumpy()[:n_valid])
        outs = [onp.concatenate(o) for o in outputs]
        result = outs[0] if len(outs) == 1 else outs
        if return_data:
            return (result, onp.concatenate(data_list),
                    onp.concatenate(label_list) if label_list else None)
        return result

    def score(self, X, y=None, eval_metric="acc", num_batch=None):
        from .gluon import metric as _metric
        data_iter = _as_iter(X, y, self.numpy_batch_size)
        data_iter.reset()
        mod = self._bound_for_predict(data_iter)
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        for i, batch in enumerate(data_iter):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            eval_metric.update(batch.label, mod.get_outputs())
        return eval_metric.get()[1]

    # -- persistence (reference checkpoint contract) -----------------------

    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               **kwargs):
        """One-call construct-and-fit (reference model.py FeedForward.create)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            optimizer=optimizer, initializer=initializer,
                            **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger)
        return model
