"""Checkpoint helpers for the symbolic API (reference python/mxnet/model.py).

``save_checkpoint`` writes ``prefix-symbol.json`` (graph) +
``prefix-####.params`` (weights with ``arg:``/``aux:`` prefixes — the
reference's on-disk contract, model.py:189), ``load_checkpoint`` reads
them back.
"""
from __future__ import annotations

from . import ndarray as nd
from . import symbol as sym_mod

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    """Load only the parameter dicts of a checkpoint."""
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:                       # unprefixed (gluon-style) entry
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class BatchEndParam:
    """Callback payload (reference model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals
