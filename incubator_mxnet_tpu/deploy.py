"""Deploy/predict surface (reference include/mxnet/c_predict_api.h +
src/c_api/c_predict_api.cc).

Export side: ``export_model`` compiles a Block (or jittable fn) forward
to StableHLO and writes three artifacts:

* ``{prefix}.stablehlo.mlir``  — human-inspectable StableHLO text of the
  compiled forward (the TPU-era analog of ``prefix-symbol.json``)
* ``{prefix}.jaxport``         — jax.export serialized executable
  (StableHLO + calling convention), reloadable without any model code
* ``{prefix}.params``          — weights in the reference TLV format
* ``{prefix}.meta.json``       — input names/shapes/dtypes

Predict side: ``load_predictor`` rebuilds a callable from the artifacts
alone — no Python model code, mirroring the reference's predict-only API
that loads symbol+params without the training stack.  The C ABI in
src/predict.cc drives exactly this loader through an embedded
interpreter, the same layering as the reference where c_predict_api.cc
is a thin C shim over the full libmxnet runtime.

Serving side: ``export_model`` additionally attempts a **batch-
polymorphic** export (``{prefix}.batch.jaxport``, symbolic leading
dim), so a loaded :class:`Predictor` accepts any batch size — the
substrate the dynamic batcher (serving/batcher.py) pads its buckets
against.  On TPU every distinct input shape is a fresh XLA compile, so
the predictor also exposes :meth:`Predictor.warmup` (pre-compile a set
of bucket sizes) and :attr:`Predictor.compile_count` (executable-cache
probe: must flatline once traffic only replays warmed shapes).
"""
from __future__ import annotations

import json
import os

import numpy as onp

import jax
import jax.export  # noqa: F401  (jax.export is a lazily-bound submodule)
import jax.numpy as jnp

from . import executor_cache as _xc

__all__ = ["export_model", "load_predictor"]


def _tuples_to_lists(tree):
    if isinstance(tree, tuple):
        return [_tuples_to_lists(t) for t in tree]
    if isinstance(tree, list):
        return [_tuples_to_lists(t) for t in tree]
    if isinstance(tree, dict):
        return {k: _tuples_to_lists(v) for k, v in tree.items()}
    return tree


def _block_forward_fn(block):
    params, apply_fn = block.functional()

    def fwd(params, *inputs):
        # keep multi-output forwards intact: the predictor exposes
        # indexed outputs (MXTPredGetOutput), so no truncation here
        return apply_fn(params, *inputs, training=False)

    return params, fwd


def export_model(model, example_inputs, prefix, params=None,
                 donate_argnums=(), aot_buckets=None,
                 sharding_rule=None, sharding_mesh=None):
    """Compile + serialize a model's forward for deployment.

    model: a gluon Block (uses ``functional()``) or a pure
    ``fn(params, *inputs)``; example_inputs: tuple of arrays fixing the
    traced shapes (static-shape contract, like the reference predictor's
    input-shape binding at MXPredCreate time).

    ``donate_argnums`` positions refer to the compiled signature
    ``fwd(params, *inputs)``: position 0 is the params pytree (never
    donatable — the predictor reuses it across calls), positions 1..n
    are the user inputs.  Donated positions are recorded in
    ``meta.json`` and re-applied by the loaded :class:`Predictor`, so
    serving executions let XLA reuse the request's input buffers for
    outputs — callers hand over the donated arrays (the batcher builds
    each padded batch fresh, so the serving path is donation-safe by
    construction).

    ``aot_buckets`` (or ``MXNET_EXPORT_AOT_BUCKETS``) additionally
    serializes one *compiled* executable per batch-bucket size next to
    the artifact (``{prefix}.aot.b{n}``), so a loading process executes
    instead of compiling — the cold-start killer for serving replicas.
    The blobs are jax/jaxlib/platform-exact (a loud versioned compat
    check falls back to recompilation on mismatch).

    ``sharding_rule`` (with ``sharding_mesh``) declares how the params
    are laid out on a mesh: either ``rule_fn(name, leaf) ->
    PartitionSpec`` (the :func:`~.parallel.mesh.shard_params`
    convention) or a pytree of PartitionSpecs matching ``params``.
    When given, the sharding analysis (``analysis/shardlint.py``) runs
    over the exported forward and meta.json gains a ``"shardlint"``
    entry: the sharding-spec tree, the per-shard HBM plan
    (``peak_hbm_bytes_per_shard``), the collective bill and any
    findings — which ``serving/placement.py`` reads as the per-shard
    footprint when placing the artifact on a mesh-sharded replica.
    """
    from .ndarray import NDArray, save as nd_save

    if hasattr(model, "functional"):
        params, fwd = _block_forward_fn(model)
    else:
        fwd = model
        if params is None:
            raise ValueError("pure-function export needs params=")
    donate_argnums = tuple(sorted(set(int(i) for i in donate_argnums)))
    if any(i == 0 for i in donate_argnums):
        raise ValueError(
            "donate_argnums position 0 is the params pytree — the "
            "predictor holds it across calls; only input positions "
            "(1..n) are donatable")
    if any(not 0 < i <= len(example_inputs) for i in donate_argnums):
        raise ValueError(
            f"donate_argnums {donate_argnums} out of range for "
            f"{len(example_inputs)} example input(s)")
    # normalize containers so the traced pytree matches what
    # _unflatten_keystr reconstructs at load time (tuples → lists;
    # keystr cannot distinguish them)
    params = _tuples_to_lists(params)

    example = tuple(
        x.data if isinstance(x, NDArray) else jnp.asarray(x)
        for x in example_inputs)

    # through the unified choke point: the export trace is a compile
    # surface like any other (sentinel site export:<name>, persistent
    # compile cache enabled at Executor construction)
    jitted = _xc.Executor(
        fwd, f"export:{os.path.basename(prefix)}",
        donate_argnums=donate_argnums).jfn
    lowered = jitted.lower(params, *example)
    with open(prefix + ".stablehlo.mlir", "w") as f:
        f.write(lowered.as_text())

    # IR lint of the forward being shipped (docs/graph_analysis.md): a
    # baked-in constant, f64 leak or host callback found NOW is one
    # found before it serves traffic.  MXNET_EXPORT_GRAPHLINT=warn
    # (default) | raise | 0.
    graphlint_summary = _export_graphlint(fwd, params, example, prefix)
    # memory plan of the same forward (analysis/memlint.py): peak-HBM
    # estimate, donated-bytes-reclaimed and the dominant buffer
    # lifetimes ride along in meta.json so the serving layer can report
    # per-model HBM without re-tracing the (opaque) deserialized graph
    memlint_summary = _export_memlint(fwd, params, example,
                                      donate_argnums, prefix)
    # sharding plan of the same forward (analysis/shardlint.py): the
    # declared spec tree, the per-shard peak and the collective bill
    # ride along so a mesh-sharded serving tier charges each replica
    # its SHARD, not the whole graph
    shardlint_summary = _export_shardlint(fwd, params, example,
                                          donate_argnums, prefix,
                                          sharding_rule, sharding_mesh)

    exported = jax.export.export(jitted)(params, *example)
    with open(prefix + ".jaxport", "wb") as f:
        f.write(exported.serialize())

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    names, wire = [], {}
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        names.append(name)
        wire[name] = NDArray(leaf)
    nd_save(prefix + ".params", wire)

    meta = {
        "format": "mxtpu_predict_v1",
        "param_names": names,
        "inputs": [{"shape": list(x.shape), "dtype": jnp.dtype(x.dtype).name}
                   for x in example],
        "outputs": [{"shape": list(s.shape), "dtype": jnp.dtype(s.dtype).name}
                    for s in jax.tree_util.tree_leaves(
                        jax.eval_shape(fwd, params, *example))],
    }
    meta["batch_export"] = _write_batch_export(jitted, params, example,
                                               prefix)
    meta["donate_argnums"] = list(donate_argnums)
    aot = _write_aot_buckets(jitted, params, example, prefix, aot_buckets)
    if aot is not None:
        meta["aot"] = aot
    if graphlint_summary is not None:
        meta["graphlint"] = graphlint_summary
    if memlint_summary is not None:
        meta["memlint"] = memlint_summary
    if shardlint_summary is not None:
        meta["shardlint"] = shardlint_summary
    with open(prefix + ".meta.json", "w") as f:
        json.dump(meta, f, indent=1)
    _write_pjrt_sidecar(prefix, params, meta)
    return meta


def _export_graphlint(fwd, params, example, prefix):
    """Lint the traced forward at export time (jaxpr passes,
    ``analysis/graphlint.py``); returns the meta.json summary or None
    when disabled.  ``warn`` mode (default) warns and records; ``raise``
    fails the export with :class:`~.error.GraphLintError`."""
    from .base import get_env
    mode = str(get_env("MXNET_EXPORT_GRAPHLINT", "warn")).strip().lower()
    if mode in ("", "0", "off", "none", "false"):
        return None
    from .analysis import graphlint
    try:
        findings = graphlint.lint_fn(
            fwd, params, *example,
            where=f"export:{os.path.basename(prefix)}")
    except Exception as e:  # mxlint: allow-broad-except(the lint is advisory in warn mode; a lint crash must never block an export)
        import warnings
        if mode == "raise":
            raise
        warnings.warn(f"export graphlint could not run ({e}); exporting "
                      "without IR analysis")
        return {"error": f"{type(e).__name__}: {e}"}
    # advisories never gate (same contract as check_traced and the
    # CLI): "findings"/"by_rule" count error severity only, so
    # raise-mode and the serving load-time warning fire only on real
    # violations and the counts agree with the breakdown
    errors = [f for f in findings if f.severity == "error"]
    by_rule: dict[str, int] = {}
    adv_by_rule: dict[str, int] = {}
    for f in findings:
        tgt = by_rule if f.severity == "error" else adv_by_rule
        tgt[f.rule] = tgt.get(f.rule, 0) + 1
    summary = {"findings": len(errors),
               "advisories": len(findings) - len(errors),
               "by_rule": by_rule,
               "advisories_by_rule": adv_by_rule,
               "details": [f.as_dict() for f in findings[:25]]}
    if errors:
        msg = (f"graphlint: {len(errors)} finding(s) in the exported "
               f"forward of {prefix!r}:\n"
               + graphlint.render(errors[:10]))
        if mode == "raise":
            from .error import GraphLintError
            raise GraphLintError(msg)
        import warnings
        warnings.warn(msg)
    return summary


def _export_memlint(fwd, params, example, donate_argnums, prefix):
    """Static memory plan of the exported forward (liveness-based
    peak-HBM estimate + donation accounting, ``analysis/memlint.py``);
    returns the meta.json summary or None when export analysis is
    disabled (same ``MXNET_EXPORT_GRAPHLINT`` gate — it is the
    export-time IR-analysis switch)."""
    from .base import get_env
    mode = str(get_env("MXNET_EXPORT_GRAPHLINT", "warn")).strip().lower()
    if mode in ("", "0", "off", "none", "false"):
        return None
    from .analysis import memlint
    try:
        rep = memlint.analyze_fn(
            fwd, params, *example,
            where=f"export:{os.path.basename(prefix)}",
            donate_argnums=donate_argnums,
            allow_undonated=(0,))   # params are held across calls
    except Exception as e:  # mxlint: allow-broad-except(the memory plan is advisory at export; a memlint crash must never block an export)
        import warnings
        warnings.warn(f"export memlint could not run ({e}); exporting "
                      "without a memory summary")
        return {"error": f"{type(e).__name__}: {e}"}
    d = rep.as_dict()
    d["buffers"] = d["buffers"][:5]
    d["findings"] = [f.as_dict() for f in rep.findings]
    return d


def _export_shardlint(fwd, params, example, donate_argnums, prefix,
                      sharding_rule, sharding_mesh):
    """Sharding analysis of the exported forward
    (``analysis/shardlint.py``); returns the meta.json summary or None
    when no sharding was declared / export analysis is disabled (same
    ``MXNET_EXPORT_GRAPHLINT`` gate as its siblings)."""
    if sharding_rule is None or sharding_mesh is None:
        return None
    from .base import get_env
    mode = str(get_env("MXNET_EXPORT_GRAPHLINT", "warn")).strip().lower()
    if mode in ("", "0", "off", "none", "false"):
        return None
    from .analysis import shardlint
    try:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        if callable(sharding_rule):
            leaf_specs = [sharding_rule(jax.tree_util.keystr(p), leaf)
                          for p, leaf in flat]
            spec_tree = jax.tree_util.tree_unflatten(treedef, leaf_specs)
        else:
            spec_tree = sharding_rule
            leaf_specs = jax.tree_util.tree_leaves(
                spec_tree, is_leaf=lambda x: x is None or isinstance(
                    x, jax.sharding.PartitionSpec))
        rep = shardlint.analyze_fn(
            fwd, params, *example, mesh=sharding_mesh,
            in_specs=(spec_tree,) + (None,) * len(example),
            where=f"export:{os.path.basename(prefix)}",
            donate_argnums=donate_argnums)
    except Exception as e:  # mxlint: allow-broad-except(the sharding plan is advisory at export; a shardlint crash must never block an export)
        import warnings
        warnings.warn(f"export shardlint could not run ({e}); exporting "
                      "without a sharding summary")
        return {"error": f"{type(e).__name__}: {e}"}
    d = rep.as_dict()
    d["collectives"] = d["collectives"][:10]
    d["sharding_spec_tree"] = {
        jax.tree_util.keystr(p): str(s if s is not None else "P()")
        for (p, _), s in zip(flat, leaf_specs)}
    return d


def _write_batch_export(jitted, params, example, prefix):
    """Shape-polymorphic twin of the static export: the leading axis of
    every input becomes one shared symbolic dim ``b``, so the serving
    batcher can execute any padding-bucket size from the same artifact
    (each concrete size still compiles once — see Predictor.warmup).
    Models that constrain the batch dim (e.g. a reshape folding it into
    a static size) can't be exported this way; the predictor then falls
    back to chunked static-batch execution."""
    path = prefix + ".batch.jaxport"
    try:
        if not all(x.ndim >= 1 for x in example):
            raise ValueError("all inputs need a leading batch axis")
        b, = jax.export.symbolic_shape("b")
        specs = [jax.ShapeDtypeStruct((b,) + tuple(x.shape[1:]), x.dtype)
                 for x in example]
        exported = jax.export.export(jitted)(params, *specs)
        blob = exported.serialize()   # serialize before open(): a failed
        with open(path, "wb") as f:   # export must not truncate the file
            f.write(blob)
        return True
    except Exception as e:  # mxlint: allow-broad-except(polymorphic export is an optional artifact; failure degrades to per-shape compilation with a warning)
        import warnings
        if os.path.exists(path):
            os.remove(path)  # no stale polymorphic artifact
        warnings.warn(
            f"batch-polymorphic export unavailable ({e}); the predictor "
            "will serve non-exported batch sizes by chunking to the "
            "traced batch size")
        return False


def _parse_aot_buckets(aot_buckets):
    """Resolve the bucket list: explicit arg wins, else the
    ``MXNET_EXPORT_AOT_BUCKETS`` env (``default``/``true`` = the
    serving batcher's padding buckets, a comma list = exactly those
    sizes — ``1`` means the single bucket [1], it is a valid size and
    must not be hijacked as a boolean — empty/``0``/``off`` = off)."""
    from .base import get_env
    if aot_buckets is None:
        raw = str(get_env("MXNET_EXPORT_AOT_BUCKETS", "")).strip().lower()
        if raw in ("", "0", "off", "none", "false"):
            return None
        if raw in ("default", "true"):
            from .serving.batcher import parse_buckets
            aot_buckets = parse_buckets()
        else:
            aot_buckets = [int(t) for t in raw.split(",") if t.strip()]
    buckets = sorted({int(b) for b in aot_buckets})
    if any(b < 1 for b in buckets):
        raise ValueError(f"AOT bucket sizes must be >= 1, got {buckets}")
    return buckets or None


def _write_aot_buckets(jitted, params, example, prefix, aot_buckets):
    """AOT layer of the artifact: one *compiled* executable per batch
    bucket, serialized with a versioned compat envelope
    (``executor_cache.serialize_executable``) as ``{prefix}.aot.b{n}``.
    ``ModelRepository.load`` + warmup then deserialize instead of
    compiling — XLA never runs in the serving replica.  Executables are
    jax/jaxlib/platform-exact; the loader's compat check falls back to
    recompilation (loudly) rather than crash on a foreign blob.
    Returns the meta.json ``"aot"`` entry or None when off/unavailable."""
    buckets = _parse_aot_buckets(aot_buckets)
    if buckets is None:
        return None
    written = []
    try:
        if not all(x.ndim >= 1 for x in example):
            raise ValueError(
                "AOT buckets need a leading batch axis on every input")
        files = {}
        for n in buckets:
            specs = [jax.ShapeDtypeStruct((n,) + tuple(x.shape[1:]),
                                          x.dtype) for x in example]
            compiled = jitted.lower(params, *specs).compile()
            blob = _xc.serialize_executable(compiled)
            # round-trip self-check BEFORE shipping: an executable
            # served from a shared compile cache can re-serialize
            # incompletely (missing kernel symbols) — a blob that does
            # not load in the exporting environment can never load
            # anywhere, and must abort the AOT layer here, not crash a
            # serving replica later.  record=False: validation, not
            # cold-start cache traffic
            _xc.deserialize_executable(blob, record=False)
            path = f"{prefix}.aot.b{n}"
            with open(path, "wb") as f:
                f.write(blob)
            written.append(path)
            files[str(n)] = os.path.basename(path)
        return {"buckets": buckets, "files": files,
                "compat": _xc.aot_compat()}
    except Exception as e:  # mxlint: allow-broad-except(AOT executables are an optional artifact layer; failure degrades to compile-at-warmup with a warning)
        import warnings
        for path in written:   # no partial bucket set: all-or-nothing
            if os.path.exists(path):
                os.remove(path)
        warnings.warn(
            f"AOT bucket export unavailable ({e}); loading processes "
            "will compile at warmup instead of deserializing")
        return None


def _write_pjrt_sidecar(prefix, params, meta):
    """Artifacts for the PURE-C++ PJRT predictor (src/pjrt_predict.cc):
    no Python at serving time, so everything the C runtime needs is
    spelled out flat —
    * ``{prefix}.pjrt.json``: the mlir main's argument list in calling
      order (param leaves in tree-flatten order, then user inputs) with
      dtype/shape, and byte offsets into
    * ``{prefix}.pjrt_params.bin``: concatenated little-endian raw
      param bytes, and
    * ``{prefix}.compile_options.pb``: a serialized CompileOptionsProto
      for PJRT_Client_Compile (generated here because C has no proto
      library).
    """
    import numpy as onp
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    args, offset = [], 0
    with open(prefix + ".pjrt_params.bin", "wb") as f:
        for path, leaf in flat:
            arr = onp.asarray(leaf)
            raw = arr.tobytes()
            args.append({"kind": "param",
                         "name": jax.tree_util.keystr(path),
                         "dtype": jnp.dtype(arr.dtype).name,
                         "shape": list(arr.shape),
                         "offset": offset, "nbytes": len(raw)})
            f.write(raw)
            offset += len(raw)
    for spec in meta["inputs"]:
        args.append({"kind": "input", "dtype": spec["dtype"],
                     "shape": spec["shape"]})
    with open(prefix + ".pjrt.json", "w") as f:
        json.dump({"format": "mxtpu_pjrt_v1", "args": args,
                   "outputs": meta["outputs"]}, f, indent=1)
    # line-oriented twin of pjrt.json for the C runtime (no JSON parser
    # in C): "arg {param|input} dtype offset nbytes ndim d0 d1 ..." /
    # "out dtype ndim d0 d1 ..."
    with open(prefix + ".pjrt.txt", "w") as f:
        for a in args:
            dims = " ".join(str(d) for d in a["shape"])
            off = a.get("offset", -1)
            nb = a.get("nbytes", -1)
            f.write(f"arg {a['kind']} {a['dtype']} {off} {nb} "
                    f"{len(a['shape'])} {dims}".rstrip() + "\n")
        for o in meta["outputs"]:
            dims = " ".join(str(d) for d in o["shape"])
            f.write(f"out {o['dtype']} {len(o['shape'])} {dims}".rstrip()
                    + "\n")
    try:
        try:
            from jaxlib import xla_client as _xc
        except ImportError:  # newer jaxlib moved it under jax._src.lib
            from jax._src.lib import _jax as _xc
        blob = _xc.CompileOptions().SerializeAsString()  # before open():
        # a failed serialization must not leave a truncated file behind
    except Exception as e:  # mxlint: allow-broad-except(compile-options blob is an optional artifact; failure warns and the PJRT-direct path recompiles)
        import warnings
        if os.path.exists(prefix + ".compile_options.pb"):
            os.remove(prefix + ".compile_options.pb")  # no stale lies
        warnings.warn(
            f"could not serialize CompileOptions ({e}); the PJRT-direct "
            "C predictor will refuse this artifact (python Predictor "
            "unaffected)")
        return
    with open(prefix + ".compile_options.pb", "wb") as f:
        f.write(blob)


class Predictor:
    """Loaded deploy artifact: ``pred(inputs) -> outputs`` (numpy).

    Mirrors MXPredCreate/SetInput/Forward/GetOutput
    (reference c_predict_api.h) as a single callable; the C ABI wraps
    this object 1:1.
    """

    def __init__(self, prefix):
        with open(prefix + ".meta.json") as f:
            self.meta = json.load(f)
        if self.meta.get("format") != "mxtpu_predict_v1":
            raise ValueError(f"{prefix}: not a mxtpu predict artifact")
        with open(prefix + ".jaxport", "rb") as f:
            self._exported = jax.export.deserialize(f.read())
        from .ndarray import load as nd_load
        loaded = nd_load(prefix + ".params")
        # rebuild the params pytree from flattened keystr names
        self._params = _unflatten_keystr(
            {k: v.data for k, v in loaded.items()})
        # both entry points go through the unified choke point
        # (executor_cache.Executor): jit's executable cache keyed on
        # concrete input shapes is (a) the warm-path dispatch and (b)
        # the compile counter the serving metrics watch
        tag = os.path.basename(prefix)
        # donation does not survive serialization: jax.export records
        # the aliasing in the module, but the re-jitted call needs its
        # own donate_argnums for the caller-side buffers to be freed —
        # re-apply the positions export_model recorded in meta.json
        # (position 0 = params, held across calls, never donated)
        self._donate = tuple(self.meta.get("donate_argnums") or ())
        self._call_ex = _xc.Executor(
            self._exported.call, f"predictor:{tag}",
            donate_argnums=self._donate)
        self._call = self._call_ex.jfn
        self._batch_call_ex = None
        self._batch_call = None
        bpath = prefix + ".batch.jaxport"
        if self.meta.get("batch_export", os.path.exists(bpath)):
            try:
                with open(bpath, "rb") as f:
                    self._batch_exported = jax.export.deserialize(f.read())
                self._batch_call_ex = _xc.Executor(
                    self._batch_exported.call, f"predictor:{tag}:batch",
                    donate_argnums=self._donate)
                self._batch_call = self._batch_call_ex.jfn
            except (OSError, ValueError) as e:
                # an artifact set copied without the polymorphic twin
                # (older tooling, partial copy) must still serve — the
                # static export fully supports the chunk/pad fallback
                import warnings
                warnings.warn(
                    f"batch-polymorphic artifact {bpath} unusable "
                    f"({e}); serving non-exported batch sizes by "
                    "chunking to the traced batch size")
        self._static_shapes = [tuple(s["shape"])
                               for s in self.meta["inputs"]]
        self._static_dtypes = [s["dtype"] for s in self.meta["inputs"]]
        # AOT layer: per-bucket *compiled* executables shipped in the
        # artifact — executing one is pure deserialization + run, no
        # XLA, so a replica that serves only AOT-covered buckets keeps
        # compile_count at ZERO from process start.  A mismatched or
        # corrupted blob is refused by the versioned compat check and
        # that bucket falls back to the traced path (recompile), loudly.
        self._aot: dict = {}
        self.aot_load_failures = 0
        for n in (self.meta.get("aot") or {}).get("buckets") or ():
            # blob paths derive from THIS prefix (like .jaxport/.params),
            # so a renamed/copied artifact set loads its own blobs — the
            # manifest's "files" entry is informational
            path = f"{prefix}.aot.b{int(n)}"
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                self._aot[int(n)] = _xc.deserialize_executable(blob)
            except (OSError, _xc.AOTCompatError) as e:
                self.aot_load_failures += 1
                import warnings
                warnings.warn(
                    f"AOT executable for bucket {n} of {prefix} "
                    f"unusable ({e}); this bucket recompiles at warmup")

    def __call__(self, *inputs):
        arrs = tuple(jnp.asarray(x) for x in inputs)
        n = self._aot_batch(arrs) if self._aot else None
        if n is not None:
            out = self._aot[n](self._params, *arrs)
        elif [tuple(a.shape) for a in arrs] == self._static_shapes:
            out = self._call(self._params, *arrs)
        else:
            out = self._flex_call(arrs)
        return jax.tree_util.tree_map(onp.asarray, out)

    # -- batched serving surface -------------------------------------

    def _aot_batch(self, arrs):
        """The batch size when ``arrs`` exactly matches the exported
        signature at an AOT-covered bucket (shared leading dim, same
        trailing shape and dtype); else None."""
        if len(arrs) != len(self._static_shapes):
            return None
        n = None
        for a, ref, dt in zip(arrs, self._static_shapes,
                              self._static_dtypes):
            if (a.ndim != len(ref) or tuple(a.shape[1:]) != tuple(ref[1:])
                    or jnp.dtype(a.dtype) != jnp.dtype(dt)):
                return None
            if n is None:
                n = int(a.shape[0])
            elif int(a.shape[0]) != n:
                return None
        return n if n in self._aot else None

    def _flex_call(self, arrs):
        """Execute at a batch size other than the traced one: the
        polymorphic export when available, else chunk/pad to the traced
        batch size (correct but pays traced-batch compute per chunk)."""
        n = self._check_batched(arrs)
        if self._batch_call is not None:
            return self._batch_call(self._params, *arrs)
        b0 = self._static_shapes[0][0]
        # each chunk is exactly b0 rows — if the artifact ships an AOT
        # executable for that size, run it instead of compiling one
        chunk_call = self._aot.get(b0, None) or self._call
        chunks = []
        for lo in range(0, n, b0):
            part = tuple(a[lo:lo + b0] for a in arrs)
            take = int(part[0].shape[0])
            if take < b0:
                part = tuple(jnp.concatenate(
                    [p, jnp.zeros((b0 - take,) + tuple(p.shape[1:]),
                                  p.dtype)]) for p in part)
            out = chunk_call(self._params, *part)
            chunks.append(jax.tree_util.tree_map(
                lambda o, k=take: o[:k], out))
        return jax.tree_util.tree_map(
            lambda *parts: jnp.concatenate(parts, axis=0), *chunks)

    def _check_batched(self, arrs):
        """Validate that inputs are the exported signature with a
        (shared) different leading dim; returns that batch size."""
        if len(arrs) != len(self._static_shapes):
            raise ValueError(
                f"model takes {len(self._static_shapes)} inputs, got "
                f"{len(arrs)}")
        n = None
        for a, ref in zip(arrs, self._static_shapes):
            if a.ndim != len(ref) or tuple(a.shape[1:]) != tuple(ref[1:]):
                raise ValueError(
                    f"input shape {tuple(a.shape)} does not match the "
                    f"exported signature {tuple(ref)} (only the leading "
                    "batch dim may differ)")
            if n is None:
                n = int(a.shape[0])
            elif int(a.shape[0]) != n:
                raise ValueError(
                    "all inputs must share one leading batch dim, got "
                    f"{[int(x.shape[0]) for x in arrs]}")
        return n

    @property
    def batch_polymorphic(self):
        return self._batch_call is not None

    @property
    def aot_buckets(self):
        """Batch sizes served by AOT-deserialized executables (no XLA
        compile in this process, ever, for these sizes)."""
        return sorted(self._aot)

    @property
    def compile_count(self):
        """Distinct executables traced so far (the executors' jit cache
        sizes; AOT executions never appear — deserialization is not
        compilation).  After ``warmup`` this must not grow while
        traffic replays warmed shapes — the serving /metrics counter
        asserts exactly that, and an all-AOT artifact keeps it at zero
        from process start."""
        return sum(ex.compile_count
                   for ex in (self._call_ex, self._batch_call_ex)
                   if ex is not None)

    def warmup(self, batch_sizes):
        """Pre-build one executable per batch size so no user request
        pays a cold XLA compile (TPU: every shape is a fresh compile).
        AOT-covered sizes execute their deserialized executable once
        (validation, not compilation)."""
        for n in batch_sizes:
            args = tuple(
                jnp.zeros((int(n),) + tuple(ref[1:]), dtype)
                for ref, dtype in zip(self._static_shapes,
                                      self._static_dtypes))
            self(*args)   # __call__ materializes to numpy: compile+run
        return self.compile_count


def _unflatten_keystr(flat: dict):
    """Invert jax.tree_util.keystr for pytrees of nested dicts, lists
    and tuples (keys look like ``['a'][0]['b']``; tuples come back as
    lists, which jax treats as the same pytree shape for calling)."""
    import re
    token = re.compile(r"\['([^']+)'\]|\[(\d+)\]")
    root: dict | list | None = None

    def ensure(container, key, make):
        if isinstance(key, int):
            while len(container) <= key:
                container.append(None)
            if container[key] is None:
                container[key] = make()
            return container[key]
        if key not in container:
            container[key] = make()
        return container[key]

    for keystr, val in flat.items():
        parts = [(m.group(1) if m.group(1) is not None else int(m.group(2)))
                 for m in token.finditer(keystr)]
        if not parts:
            parts = [keystr]
        kinds = [list if isinstance(p, int) else dict for p in parts]
        if root is None:
            root = kinds[0]()
        node = root
        for i, p in enumerate(parts[:-1]):
            node = ensure(node, p, kinds[i + 1])
        last = parts[-1]
        if isinstance(last, int):
            while len(node) <= last:
                node.append(None)
            node[last] = val
        else:
            node[last] = val
    return root if root is not None else {}


def load_predictor(prefix):
    return Predictor(prefix)
