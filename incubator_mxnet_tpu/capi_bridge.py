"""Python side of the MX* C API (src/c_api.cc).

Architecture parity with the reference's C API boundary: the reference's
``src/c_api/c_api.cc`` (~400 ``MX*`` functions over
include/mxnet/c_api.h) is a thin C shim translating C types into calls
on the C++ runtime.  Here the runtime *is* the XLA/PJRT stack driven by
this package, so the C shim (src/c_api.cc, embedded CPython like
src/predict.cc) translates C types into calls on the functions below.
Every function in this module takes/returns only C-marshallable values
(ints, bytes, str, tuples/lists thereof, or opaque object handles the C
side holds strong references to).

Keep this module import-light: the C ABI is used from deploy contexts
where startup latency matters.
"""
from __future__ import annotations

import ast

import numpy as onp

__all__ = ["DTYPE_CODES", "DTYPE_NAMES"]

# Reference dtype enum (include/mxnet/base.h via mshadow type flags:
# kFloat32=0 ... kInt64=6, kBool=7; bfloat16 carries the reference's
# mshadow::kBfloat16=12) extended with the remaining fixed-width ints.
DTYPE_NAMES = {
    0: "float32", 1: "float64", 2: "float16", 3: "uint8", 4: "int32",
    5: "int8", 6: "int64", 7: "bool", 8: "int16", 9: "uint16",
    10: "uint32", 11: "uint64", 12: "bfloat16",
}
DTYPE_CODES = {v: k for k, v in DTYPE_NAMES.items()}


def _mx():
    import incubator_mxnet_tpu as mx
    return mx


def _nd():
    from incubator_mxnet_tpu import nd
    return nd


def version() -> int:
    return 20000  # 2.0.0, MXNET_VERSION style (major*10000+minor*100+patch)


def seed(s: int) -> None:
    _mx().random.seed(int(s))


def waitall() -> None:
    _nd().waitall()


# ---------------------------------------------------------------------------
# NDArray
# ---------------------------------------------------------------------------

def _ctx(dev_type: int, dev_id: int):
    from incubator_mxnet_tpu.context import Context
    return Context(Context.devtype2str[int(dev_type)], int(dev_id))


def create(shape, dtype_code: int, dev_type: int, dev_id: int):
    nd = _nd()
    return nd.zeros(tuple(int(d) for d in shape),
                    dtype=DTYPE_NAMES[int(dtype_code)],
                    ctx=_ctx(dev_type, dev_id))


def set_bytes(arr, data: bytes) -> None:
    """SyncCopyFromCPU: in-place host->array copy (full buffer)."""
    import jax.numpy as jnp
    np_dtype = onp.dtype(jnp.dtype(arr.dtype))  # ml_dtypes covers bf16
    host = onp.frombuffer(data, dtype=np_dtype)
    arr[:] = host.reshape(arr.shape)


def set_floats(arr, data: bytes) -> None:
    """SyncCopyFromCPU float32 variant (the reference predict-style path:
    host buffer is float32, cast to the array dtype on device)."""
    host = onp.frombuffer(data, dtype=onp.float32).reshape(arr.shape)
    arr[:] = host


def get_bytes(arr) -> bytes:
    a = arr.asnumpy()
    return a.tobytes()


def get_floats(arr) -> bytes:
    return arr.asnumpy().astype(onp.float32).tobytes()


def get_shape(arr):
    return tuple(int(d) for d in arr.shape)


def get_dtype(arr) -> int:
    from incubator_mxnet_tpu.base import dtype_name
    return DTYPE_CODES[dtype_name(arr.dtype)]


def get_context(arr):
    ctx = arr.ctx
    return int(ctx.device_typeid), int(ctx.device_id)


def slice_(arr, begin: int, end: int):
    return arr.slice([int(begin)], [int(end)])


def at(arr, idx: int):
    return arr[int(idx)]


def reshape(arr, dims):
    return arr.reshape(tuple(int(d) for d in dims))


def wait_to_read(arr) -> None:
    arr.wait_to_read()


def save(fname: str, names, arrs) -> None:
    nd = _nd()
    if names:
        nd.save(fname, dict(zip(names, arrs)))
    else:
        nd.save(fname, list(arrs))


def load(fname: str):
    nd = _nd()
    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return names, [data[n] for n in names]
    return [], list(data)


# ---------------------------------------------------------------------------
# Operator invocation (MXImperativeInvoke)
# ---------------------------------------------------------------------------

def list_ops():
    from incubator_mxnet_tpu.ops import registry
    return registry.list_ops()


def _parse_val(s: str):
    """Reference op params arrive as strings (dmlc::Parameter style);
    accept python/mxnet literal syntax: ints, floats, bools, tuples."""
    s = s.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def invoke(op_name: str, inputs, keys, vals):
    from incubator_mxnet_tpu.ops import registry
    kwargs = {k: _parse_val(v) for k, v in zip(keys, vals)}
    out = registry.invoke(op_name, *inputs, **kwargs)
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


# ---------------------------------------------------------------------------
# KVStore
# ---------------------------------------------------------------------------

def kv_create(type_str: str):
    import incubator_mxnet_tpu as mx
    return mx.kv.create(type_str)


def kv_init(kv, key: str, arr) -> None:
    kv.init(key, arr)


def kv_push(kv, key: str, arr, priority: int) -> None:
    kv.push(key, arr, priority=int(priority))


def kv_pull(kv, key: str, out, priority: int) -> None:
    kv.pull(key, out=out, priority=int(priority))


def kv_type(kv) -> str:
    return kv.type


def kv_rank(kv) -> int:
    return int(kv.rank)


def kv_size(kv) -> int:
    return int(kv.num_workers)


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------

def sym_from_json(json_str: str):
    from incubator_mxnet_tpu import symbol as sym
    return sym.load_json(json_str)


def sym_from_file(fname: str):
    from incubator_mxnet_tpu import symbol as sym
    return sym.load(fname)


def sym_to_json(s) -> str:
    return s.tojson()


def sym_outputs(s):
    return list(s.list_outputs())


def sym_arguments(s):
    return list(s.list_arguments())
