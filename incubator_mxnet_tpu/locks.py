"""Named lock factory — the one place the control plane makes locks.

Every ``threading.Lock``/``RLock``/``Condition`` the threaded control
plane holds (fleet, router tier, autoscaler, sessions, batchers,
kvstore, engine, loadgen, observability) is constructed here with a
stable dotted *name* (``fleet.state``, ``placer.ledger``,
``sessions.registry`` — docs/static_analysis.md "locklint" for the
naming convention).  The name is what makes lock discipline analyzable:

* **statically** — ``analysis/locklint.py`` resolves ``named_lock``
  bindings to their names and builds the cross-module lock-order graph
  (MX-LOCK002), something attribute-regex heuristics over bare
  ``threading.Lock()`` constructions could only do per module;
* **dynamically** — under ``MXNET_LOCK_WITNESS=1`` this factory
  returns instrumented wrappers (``analysis/lockwitness.py``) that
  maintain per-thread held-sets and a global acquisition-order graph,
  banking a typed :class:`~.error.LockOrderError` on any observed
  order cycle.

Flag-off cost: the witness decision is ONE module-bool branch at
*construction* time — ``named_lock`` then returns a bare
``threading.Lock``, so the acquire/release hot path carries zero
wrapper overhead (pinned by ``tests/test_locklint.py``'s
microbenchmark: < 2 µs per acquire/release pair).

This module is deliberately a leaf (stdlib only, no framework
imports): it is imported by ``base.py`` and the observability layer
before the rest of the package exists, and the witness module is
loaded by file exactly like the mxlint CLI loads its analyzer — so
enabling the witness can never introduce an import cycle.
"""
from __future__ import annotations

import os
import threading

__all__ = ["named_lock", "named_rlock", "named_condition",
           "witness_enabled", "set_witness"]

_TRUTHY = ("1", "true", "yes", "on")


def _env_flag():
    # documented in docs/env_vars.md (MX-ENV001); read directly —
    # base.get_env would import jax into this leaf module
    return os.environ.get(
        "MXNET_LOCK_WITNESS", "").strip().lower() in _TRUTHY


#: construction-time gate — one module-bool branch per factory call.
_witness: bool = _env_flag()

_WITNESS_MOD = "incubator_mxnet_tpu.analysis.lockwitness"


def _witness_module():
    """The lockwitness module, loaded by FILE under its canonical name
    (and registered in ``sys.modules`` so a later package import sees
    the same instance).  File-loading keeps this path cycle-proof:
    ``base.py`` constructs named locks while the package is still
    importing, and a normal ``from .analysis import lockwitness``
    would re-enter the half-initialized package."""
    import sys
    mod = sys.modules.get(_WITNESS_MOD)
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "analysis", "lockwitness.py")
        spec = importlib.util.spec_from_file_location(_WITNESS_MOD, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[_WITNESS_MOD] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            sys.modules.pop(_WITNESS_MOD, None)
            raise
    return mod


def witness_enabled() -> bool:
    """Whether new ``named_*`` constructions are witness-instrumented."""
    return _witness


def set_witness(flag):
    """Toggle witnessing for locks constructed AFTER this call;
    ``None`` re-reads ``MXNET_LOCK_WITNESS``.  Existing locks keep
    whatever shape they were built with (a bare lock cannot be
    retrofitted), so tests flip this before constructing the component
    under test.  Returns the previous value."""
    global _witness
    prev = _witness
    _witness = _env_flag() if flag is None else bool(flag)
    if _witness:
        _witness_module().set_enabled(True)
    return prev


def named_lock(name: str):
    """A ``threading.Lock`` carrying a stable dotted name.

    Flag-off: returns a bare ``threading.Lock`` (zero acquire
    overhead).  Under ``MXNET_LOCK_WITNESS=1``: returns a
    ``lockwitness.WitnessLock`` with the full acquire/release
    signature (``blocking=``/``timeout=`` included — the flight
    recorder's signal path does non-blocking tries)."""
    if _witness:
        return _witness_module().WitnessLock(name)
    return threading.Lock()


def named_rlock(name: str):
    """Reentrant variant of :func:`named_lock` — witness bookkeeping
    counts reacquisition depth instead of fabricating self-edges."""
    if _witness:
        return _witness_module().WitnessRLock(name)
    return threading.RLock()


def named_condition(name: str, lock=None):
    """A ``threading.Condition`` over a named lock.

    ``lock`` may be an earlier ``named_lock`` result (the
    ``ps_server`` pattern — one mutex, one condition over it) or
    ``None`` for a private lock.  Witness-on, ``wait()`` correctly
    drops the lock from the per-thread held-set for the duration of
    the wait (a Condition wait *releases*, which is why audited waits
    are exempt from MX-LOCK003)."""
    if _witness:
        return _witness_module().WitnessCondition(name, lock)
    return threading.Condition(lock)
