"""Library/build information (reference python/mxnet/libinfo.py).

The reference locates libmxnet.so for ctypes; here the native runtime
is libmxtpu.so (+ the optional libmxtapi.so C API), built from src/.
"""
from __future__ import annotations

import os

__version__ = "0.1.0"


def find_lib_path():
    """Paths of the native runtime libraries that exist on disk
    (reference libinfo.py:25).  Canonical location comes from the
    native loader (one source of truth)."""
    from . import native as _native
    runtime = _native._LIB_PATH
    candidates = [runtime,
                  os.path.join(os.path.dirname(runtime), "libmxtapi.so")]
    found = [p for p in candidates if os.path.exists(p)]
    if not found:
        raise RuntimeError(
            "native runtime library not found; build it with `make -C src` "
            f"(searched {candidates})")
    return found


def find_include_path():
    """Path of the C ABI headers (reference libinfo.py find_include_path)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inc = os.path.join(repo, "src", "include")
    if not os.path.isdir(inc):
        raise RuntimeError(f"include path not found at {inc}")
    return inc
