"""Dynamic external-op libraries (reference python/mxnet/library.py
``load`` → C++ ``MXLoadLib`` + include/mxnet/lib_api.h).

``load("libfoo.so")`` dlopens a library implementing the C ABI in
src/include/mxt/ext_op.h and registers every op it exports in the op
registry.  Kernels run host-side via ``jax.pure_callback`` — inside jit
the callback becomes a host transfer + C call + transfer back, the
documented slow-path escape hatch (the reference's external ops are the
same: opt-in custom kernels outside the compiled graph).
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp

import jax
import jax.numpy as jnp

from .ops.registry import Op, _OPS, _lock

__all__ = ["load", "loaded_libraries"]

_LIBS: dict[str, ctypes.CDLL] = {}

_MAX_NDIM = 8


def loaded_libraries():
    return dict(_LIBS)


def _declare(lib: ctypes.CDLL):
    i64pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))
    lib.mxt_ext_abi_version.restype = ctypes.c_int
    lib.mxt_ext_num_ops.restype = ctypes.c_int
    lib.mxt_ext_op_name.restype = ctypes.c_char_p
    lib.mxt_ext_op_name.argtypes = [ctypes.c_int]
    lib.mxt_ext_op_num_inputs.restype = ctypes.c_int
    lib.mxt_ext_op_num_inputs.argtypes = [ctypes.c_int]
    lib.mxt_ext_op_infer_shape.restype = ctypes.c_int
    lib.mxt_ext_op_infer_shape.argtypes = [
        ctypes.c_int, ctypes.c_int, i64pp,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int)]
    lib.mxt_ext_op_forward.restype = ctypes.c_int
    lib.mxt_ext_op_forward.argtypes = [
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)), i64pp,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_float)]
    return lib


def _shapes_to_c(shapes):
    n = len(shapes)
    ndims = (ctypes.c_int * n)(*[len(s) for s in shapes])
    rows = []
    for s in shapes:
        rows.append((ctypes.c_int64 * max(len(s), 1))(*[int(d) for d in s]))
    ptrs = (ctypes.POINTER(ctypes.c_int64) * n)(
        *[ctypes.cast(r, ctypes.POINTER(ctypes.c_int64)) for r in rows])
    return ptrs, ndims, rows  # rows kept alive by caller


def _infer_shape(lib, idx, shapes):
    ptrs, ndims, _keep = _shapes_to_c(shapes)
    out_shape = (ctypes.c_int64 * _MAX_NDIM)()
    out_ndim = ctypes.c_int(0)
    rc = lib.mxt_ext_op_infer_shape(idx, len(shapes), ptrs, ndims,
                                    out_shape, ctypes.byref(out_ndim))
    if rc != 0:
        raise RuntimeError(f"external op infer_shape failed (rc={rc})")
    return tuple(int(out_shape[i]) for i in range(out_ndim.value))


def _make_ext_fn(lib, idx, name):
    def host_kernel(*arrays):
        arrays = [onp.ascontiguousarray(onp.asarray(a), onp.float32)
                  for a in arrays]
        shapes = [a.shape for a in arrays]
        out_shape = _infer_shape(lib, idx, shapes)
        out = onp.empty(out_shape, onp.float32)
        ptrs, ndims, _keep = _shapes_to_c(shapes)
        data_ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        rc = lib.mxt_ext_op_forward(
            idx, len(arrays), data_ptrs, ptrs, ndims,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise RuntimeError(f"external op {name!r} forward failed "
                               f"(rc={rc})")
        return out

    def fn(*arrays):
        shapes = [tuple(a.shape) for a in arrays]
        out_shape = _infer_shape(lib, idx, shapes)
        result = jax.ShapeDtypeStruct(out_shape, jnp.float32)
        return jax.pure_callback(
            host_kernel, result,
            *[jnp.asarray(a, jnp.float32) for a in arrays])

    fn.__name__ = name
    fn.__doc__ = (f"External op {name!r} (C ABI, src/include/mxt/ext_op.h; "
                  "reference lib_api.h). Host-callback execution.")
    return fn


def load(path, verbose=True, allow_override=False):
    """Load an external-op library (reference mx.library.load →
    MXLoadLib).  Returns the list of op names registered.  Refuses to
    shadow a builtin op unless ``allow_override=True`` (a silent clobber
    would reroute e.g. every relu through a host callback)."""
    path = os.path.abspath(path)
    lib = _declare(ctypes.CDLL(path))
    abi = lib.mxt_ext_abi_version()
    if abi != 1:
        raise RuntimeError(
            f"{path}: external-op ABI version {abi} unsupported (want 1)")
    names = []
    n = lib.mxt_ext_num_ops()
    for idx in range(n):
        name = lib.mxt_ext_op_name(idx).decode()
        if name in _OPS and not allow_override:
            raise ValueError(
                f"{path}: op {name!r} already registered; pass "
                "allow_override=True to replace the builtin")
        nin = lib.mxt_ext_op_num_inputs(idx)
        op = Op(name, _make_ext_fn(lib, idx, name), differentiable=False,
                num_inputs=nin)
        with _lock:
            _OPS[name] = op
        names.append(name)
    _LIBS[path] = lib
    # expose in the nd namespace like generated wrappers
    from . import ndarray as nd_mod
    for name in names:
        if not hasattr(nd_mod, name):
            setattr(nd_mod, name, nd_mod._make_wrapper(name))
    if verbose:
        print(f"[mxt.library] loaded {len(names)} external op(s) from "
              f"{path}: {names}")
    return names
