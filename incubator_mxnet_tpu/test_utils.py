"""Test helpers (reference python/mxnet/test_utils.py, 2,587 LoC).

The load-bearing pieces replicated per SURVEY.md §4: numeric assertions,
finite-difference gradient checking, and ``check_consistency`` — the
cross-backend oracle (CPU↔GPU in the reference, CPU↔TPU here).
"""
from __future__ import annotations

import numpy as onp

from .context import Context, cpu, current_context, tpu
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["default_context", "assert_almost_equal", "almost_equal",
           "check_numeric_gradient", "check_consistency", "rand_ndarray",
           "rand_shape_nd", "same", "with_seed", "assert_exception",
           "rand_sparse_ndarray", "check_symbolic_forward",
           "check_symbolic_backward", "compare_optimizer", "EnvManager",
           "DummyIter"]

_default_ctx = None


def default_context() -> Context:
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return onp.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-6, names=("a", "b")):
    a_np, b_np = _as_np(a), _as_np(b)
    if not onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=True):
        err = onp.abs(a_np - b_np)
        rel = err / (onp.abs(b_np) + atol)
        raise AssertionError(
            f"{names[0]} != {names[1]}: max abs err {err.max():g}, "
            f"max rel err {rel.max():g} (rtol={rtol}, atol={atol})\n"
            f"{names[0]}: {a_np.ravel()[:8]}\n{names[1]}: {b_np.ravel()[:8]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype)
    arr = nd.array(data, ctx=ctx or default_context())
    if stype == "row_sparse":
        from .ndarray import sparse
        mask = onp.random.rand(shape[0]) < (density if density is not None else 0.5)
        data[~mask] = 0
        return sparse.cast_storage(nd.array(data, ctx=ctx or default_context()),
                                   "row_sparse")
    if stype == "csr":
        from .ndarray import sparse
        mask = onp.random.rand(*shape) < (density if density is not None else 0.5)
        return sparse.cast_storage(nd.array(data * mask,
                                            ctx=ctx or default_context()), "csr")
    return arr


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check (reference test_utils.py:987).

    fn: callable(list-of-NDArray) -> scalar NDArray.
    inputs: list of NDArrays; each gets attach_grad + analytic backward,
    then central differences validate every element.
    """
    from . import autograd

    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = fn(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    for i, x in enumerate(inputs):
        flat = x.asnumpy().astype("float64").ravel()
        num_grad = onp.zeros_like(flat)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            f_pos = float(fn(*inputs).asnumpy())
            flat[j] = orig - eps
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            f_neg = float(fn(*inputs).asnumpy())
            flat[j] = orig
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            num_grad[j] = (f_pos - f_neg) / (2 * eps)
        assert_almost_equal(analytic[i].ravel(), num_grad, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


def check_consistency(fn, inputs_np, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run fn on several contexts and cross-check outputs
    (reference test_utils.py:1428 — the cross-backend oracle)."""
    ctx_list = ctx_list or [cpu(), tpu()]
    results = []
    for ctx in ctx_list:
        args = [nd.array(a, ctx=ctx) for a in inputs_np]
        out = fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for ctx, res in zip(ctx_list[1:], results[1:]):
        for i, (r, o) in enumerate(zip(ref, res)):
            assert_almost_equal(r, o, rtol=rtol, atol=atol,
                                names=(f"{ctx_list[0]}[{i}]", f"{ctx}[{i}]"))
    return results


def list_gpus():
    return []


def download(url, fname=None, dirname=None, overwrite=False, retries=5):
    raise RuntimeError("network egress is unavailable in this environment")


# ---------------------------------------------------------------------------
# round-3 additions: the remaining load-bearing helpers of the
# reference's test_utils.py / tests/python/unittest/common.py surface
# ---------------------------------------------------------------------------

def with_seed(seed=None):
    """Decorator: reproducible per-test RNG with the failure banner
    (reference tests/python/unittest/common.py:with_seed).  Seeds both
    numpy and the framework stream; on failure prints the seed so the
    run can be replayed with MXNET_TEST_SEED."""
    import functools
    import os
    import sys

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            env = os.environ.get("MXNET_TEST_SEED")
            # MXNET_TEST_COUNT repeats the body with fresh seeds — the
            # hook tools/flakiness_checker.py drives (reference
            # common.py with_seed/ flakiness_checker contract)
            count = max(int(os.environ.get("MXNET_TEST_COUNT", "1")), 1)
            if count > 1 and seed is not None and env is None:
                print(f"*** MXNET_TEST_COUNT={count}: decorator-pinned "
                      f"seed {seed} is replaced by fresh per-trial seeds "
                      "***", file=sys.stderr)
            ret = None
            for trial in range(count):
                this_seed = (int(env) if env is not None
                             else seed if seed is not None and count == 1
                             else int.from_bytes(os.urandom(4), "little"))
                onp.random.seed(this_seed)
                from . import random as _random
                _random.seed(this_seed)
                try:
                    ret = fn(*args, **kwargs)
                except Exception:
                    print(f"*** test failed at trial {trial + 1}/{count} "
                          f"with seed {this_seed}: set "
                          f"MXNET_TEST_SEED={this_seed} to reproduce ***",
                          file=sys.stderr)
                    raise
            return ret
        return wrapper

    return deco


def assert_exception(fn, exception_type, *args, **kwargs):
    """fn(*args) must raise exception_type (reference test_utils.py)."""
    try:
        fn(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(
        f"{fn} did not raise {exception_type.__name__}")


def rand_sparse_ndarray(shape, stype, density=0.5, dtype="float32"):
    """Random sparse array + its constituent buffers
    (reference test_utils.py:388 rand_sparse_ndarray)."""
    from .ndarray import sparse
    arr = rand_ndarray(shape, stype=stype, density=density, dtype=dtype)
    if stype == "row_sparse":
        return arr, (onp.asarray(arr._rs_values), onp.asarray(arr._rs_indices))
    if stype == "csr":
        return arr, (onp.asarray(arr._csr_data), onp.asarray(arr._csr_indices),
                     onp.asarray(arr._csr_indptr))
    raise ValueError(f"not a sparse stype: {stype}")


def check_symbolic_forward(sym, locations, expected, rtol=1e-4, atol=1e-5,
                           ctx=None):
    """Bind a symbol, run forward, compare each output
    (reference test_utils.py check_symbolic_forward)."""
    arg_names = sym.list_arguments()
    if isinstance(locations, (list, tuple)):
        locations = dict(zip(arg_names, locations))
    ex = sym.simple_bind(
        ctx=ctx, **{k: onp.asarray(v).shape for k, v in locations.items()})
    for k, v in locations.items():
        if k in ex.arg_dict:
            ex.arg_dict[k][:] = onp.asarray(v)
    outs = ex.forward()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o.asnumpy(), onp.asarray(e), rtol=rtol,
                            atol=atol, names=(f"out[{i}]", f"expected[{i}]"))
    return outs


def check_symbolic_backward(sym, locations, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-5, ctx=None):
    """Bind, forward, backward with given head gradients, compare arg
    grads (reference test_utils.py check_symbolic_backward)."""
    arg_names = sym.list_arguments()
    if isinstance(locations, (list, tuple)):
        locations = dict(zip(arg_names, locations))
    if isinstance(expected_grads, (list, tuple)):
        expected_grads = dict(zip(arg_names, expected_grads))
    ex = sym.simple_bind(
        ctx=ctx, **{k: onp.asarray(v).shape for k, v in locations.items()})
    for k, v in locations.items():
        if k in ex.arg_dict:
            ex.arg_dict[k][:] = onp.asarray(v)
    ex.forward(is_train=True)
    ex.backward([nd.array(g) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])])
    for name, exp in expected_grads.items():
        assert_almost_equal(ex.grad_dict[name].asnumpy(), onp.asarray(exp),
                            rtol=rtol, atol=atol,
                            names=(f"grad[{name}]", "expected"))
    return ex


def compare_optimizer(opt1, opt2, shapes=((4, 3),), dtype="float32",
                      w_stype="default", g_stype="default", rtol=1e-4,
                      atol=1e-5, nsteps=3):
    """Run two optimizers over identical weight/grad streams and demand
    identical trajectories (reference test_utils.py compare_optimizer)."""
    for shape in shapes:
        w_np = onp.random.uniform(-1, 1, size=shape).astype(dtype)
        w1 = nd.array(w_np.copy())
        w2 = nd.array(w_np.copy())
        s1 = opt1.create_state(0, w1)
        s2 = opt2.create_state(0, w2)
        for _ in range(nsteps):
            g_np = onp.random.uniform(-1, 1, size=shape).astype(dtype)
            opt1.update(0, w1, nd.array(g_np.copy()), s1)
            opt2.update(0, w2, nd.array(g_np.copy()), s2)
            assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=rtol,
                                atol=atol, names=("opt1_w", "opt2_w"))


class EnvManager:
    """Scoped environment variable (reference test_utils.py EnvManager)."""

    def __init__(self, key, val):
        self._key = key
        self._val = val
        self._prev = None

    def __enter__(self):
        import os
        self._prev = os.environ.get(self._key)
        os.environ[self._key] = self._val
        return self

    def __exit__(self, *exc):
        import os
        if self._prev is None:
            os.environ.pop(self._key, None)
        else:
            os.environ[self._key] = self._prev


class DummyIter:
    """Endless repetition of one batch (reference test_utils.py DummyIter)."""

    def __init__(self, real_iter):
        self._iter = real_iter
        self._batch = next(iter(real_iter))
        self.batch_size = getattr(real_iter, "batch_size", None)
        self.provide_data = getattr(real_iter, "provide_data", None)
        self.provide_label = getattr(real_iter, "provide_label", None)

    def __iter__(self):
        while True:
            yield self._batch

    def next(self):
        return self._batch

    def reset(self):
        pass
