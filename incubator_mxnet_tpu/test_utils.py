"""Test helpers (reference python/mxnet/test_utils.py, 2,587 LoC).

The load-bearing pieces replicated per SURVEY.md §4: numeric assertions,
finite-difference gradient checking, and ``check_consistency`` — the
cross-backend oracle (CPU↔GPU in the reference, CPU↔TPU here).
"""
from __future__ import annotations

import numpy as onp

from .context import Context, cpu, current_context, tpu
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["default_context", "assert_almost_equal", "almost_equal",
           "check_numeric_gradient", "check_consistency", "rand_ndarray",
           "rand_shape_nd", "same"]

_default_ctx = None


def default_context() -> Context:
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return onp.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-6, names=("a", "b")):
    a_np, b_np = _as_np(a), _as_np(b)
    if not onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=True):
        err = onp.abs(a_np - b_np)
        rel = err / (onp.abs(b_np) + atol)
        raise AssertionError(
            f"{names[0]} != {names[1]}: max abs err {err.max():g}, "
            f"max rel err {rel.max():g} (rtol={rtol}, atol={atol})\n"
            f"{names[0]}: {a_np.ravel()[:8]}\n{names[1]}: {b_np.ravel()[:8]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype)
    arr = nd.array(data, ctx=ctx or default_context())
    if stype == "row_sparse":
        from .ndarray import sparse
        mask = onp.random.rand(shape[0]) < (density if density is not None else 0.5)
        data[~mask] = 0
        return sparse.cast_storage(nd.array(data, ctx=ctx or default_context()),
                                   "row_sparse")
    if stype == "csr":
        from .ndarray import sparse
        mask = onp.random.rand(*shape) < (density if density is not None else 0.5)
        return sparse.cast_storage(nd.array(data * mask,
                                            ctx=ctx or default_context()), "csr")
    return arr


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference gradient check (reference test_utils.py:987).

    fn: callable(list-of-NDArray) -> scalar NDArray.
    inputs: list of NDArrays; each gets attach_grad + analytic backward,
    then central differences validate every element.
    """
    from . import autograd

    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = fn(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    for i, x in enumerate(inputs):
        flat = x.asnumpy().astype("float64").ravel()
        num_grad = onp.zeros_like(flat)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            f_pos = float(fn(*inputs).asnumpy())
            flat[j] = orig - eps
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            f_neg = float(fn(*inputs).asnumpy())
            flat[j] = orig
            x._set_data(flat.reshape(x.shape).astype(str(x.dtype)))
            num_grad[j] = (f_pos - f_neg) / (2 * eps)
        assert_almost_equal(analytic[i].ravel(), num_grad, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


def check_consistency(fn, inputs_np, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run fn on several contexts and cross-check outputs
    (reference test_utils.py:1428 — the cross-backend oracle)."""
    ctx_list = ctx_list or [cpu(), tpu()]
    results = []
    for ctx in ctx_list:
        args = [nd.array(a, ctx=ctx) for a in inputs_np]
        out = fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for ctx, res in zip(ctx_list[1:], results[1:]):
        for i, (r, o) in enumerate(zip(ref, res)):
            assert_almost_equal(r, o, rtol=rtol, atol=atol,
                                names=(f"{ctx_list[0]}[{i}]", f"{ctx}[{i}]"))
    return results


def list_gpus():
    return []


def download(url, fname=None, dirname=None, overwrite=False, retries=5):
    raise RuntimeError("network egress is unavailable in this environment")
