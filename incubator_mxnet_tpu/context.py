"""Device context abstraction.

TPU-native counterpart of the reference ``Context`` (include/mxnet/base.h:90-116
and python/mxnet/context.py).  A ``Context`` names a logical device
(``cpu()``, ``gpu()``, ``tpu()``); it resolves lazily to a concrete JAX
device.  On machines without the requested platform the context falls back
to the default JAX backend so code written for ``tpu()`` runs under the
CPU test harness unchanged (this is the ``check_consistency`` bridge —
reference python/mxnet/test_utils.py:1428).
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus",
           "num_tpus", "gpu_memory_info", "tpu_memory_info",
           "memory_summary"]

_context_stack = threading.local()


def _local(devs):
    """Only this process's devices: in multi-controller mode
    (jax.distributed) an array must live on an addressable device."""
    mine = [d for d in devs if d.process_index == jax.process_index()]
    return mine or list(devs)


def _devices_for(platform: str):
    try:
        return _local(jax.devices(platform))
    except RuntimeError:
        return []


class Context:
    """A logical device: ``Context('tpu', 0)``.

    devtypes mirror the reference enum (cpu=1, gpu=2, cpu_pinned=3,
    cpu_shared=5) with tpu added as the first-class accelerator type.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devstr2type:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- resolution to a physical JAX device ------------------------------
    @property
    def jax_device(self):
        """The concrete jax.Device this context maps to.

        tpu→tpu devices when present, else the default backend (CPU test
        harness); gpu→tpu/gpu accelerator if present (so reference scripts
        that say ``mx.gpu(0)`` run on the TPU chip), else default.
        """
        platform = self.device_type
        if platform in ("cpu_pinned", "cpu_shared"):
            platform = "cpu"
        devs = _devices_for(platform)
        if not devs and platform == "gpu":
            devs = _devices_for("tpu")
        if not devs and platform == "tpu":
            # Some TPU-attached platforms register under a different name
            # (e.g. the experimental 'axon' tunnel); jax.devices() returns
            # the accelerator first.
            default = _local(jax.devices())
            if default and default[0].platform != "cpu":
                devs = default
        if not devs:
            devs = _local(jax.devices())
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Release cached device memory back to the platform.

        The reference frees the GPU pool (storage per-device release);
        under PJRT, buffers are freed eagerly when unreferenced, so this
        only triggers a GC-level sweep.
        """
        import gc

        gc.collect()

    def __enter__(self):
        if not hasattr(_context_stack, "contexts"):
            _context_stack.contexts = []
        _context_stack.contexts.append(self)
        return self

    def __exit__(self, *exc):
        _context_stack.contexts.pop()


def current_context() -> Context:
    """The innermost ``with ctx:`` context, defaulting to cpu(0).

    Matches reference semantics (python/mxnet/context.py current_context):
    default context is cpu; ops placed explicitly via ctx args.
    """
    stack = getattr(_context_stack, "contexts", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_devices_for("gpu"))


def num_tpus() -> int:
    devs = _devices_for("tpu")
    if not devs:
        devs = [d for d in jax.devices() if d.platform not in ("cpu",)]
    return len(devs)


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes on an accelerator (reference
    python/mxnet/context.py:279 gpu_memory_info over cudaMemGetInfo).

    TPU mapping: PJRT ``device.memory_stats()`` — the HBM-pool statistics
    the reference's GPUPooledStorageManager tracked (SURVEY.md §2.1
    storage row).  Falls back to (0, 0) on backends that expose no
    stats (the virtual-CPU test harness).
    """
    devs = [d for d in _local(jax.devices()) if d.platform != "cpu"] \
        or _local(jax.devices())
    if not 0 <= device_id < len(devs):
        raise ValueError(
            f"device_id {device_id} out of range (have {len(devs)})")
    dev = devs[device_id]
    stats = dev.memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return (total - used, total)


def tpu_memory_info(device_id: int = 0):
    return gpu_memory_info(device_id)


def memory_summary(device_id: int = 0):
    """Human-readable device-memory report (the storage-profiler hook of
    reference storage_profiler.cc, surfaced Python-side)."""
    devs = _local(jax.devices())
    if not 0 <= device_id < len(devs):
        raise ValueError(
            f"device_id {device_id} out of range (have {len(devs)})")
    dev = devs[device_id]
    stats = dev.memory_stats() or {}
    lines = [f"device {dev}"]
    for k in sorted(stats):
        lines.append(f"  {k}: {stats[k]}")
    if not stats:
        lines.append("  (backend exposes no memory statistics)")
    return "\n".join(lines)
