"""Logging helpers (reference python/mxnet/log.py): a `get_logger`
with the reference's level/format conventions."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger"]

_FORMAT = "%(asctime)s [%(levelname)s] %(name)s %(message)s"


def get_logger(name=None, filename=None, filemode=None, level=None):
    """Create/fetch a logger configured the reference way (log.py:43):
    optional file sink, WARNING default level, shared format."""
    logger = logging.getLogger(name)
    if name is None:
        # reference log.py only configures NAMED loggers; mutating the
        # root logger would hijack the host application's logging setup
        return logger
    if getattr(logger, "_mxt_configured", False):
        if level is not None:
            logger.setLevel(level)
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.setLevel(level if level is not None else logging.WARNING)
    logger._mxt_configured = True
    return logger


getLogger = get_logger
