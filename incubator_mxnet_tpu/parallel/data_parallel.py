"""Data-parallel training step builder.

The TPU equivalent of KVStore('device') + Trainer (reference
trainer.py:380 _allreduce_grads): instead of pushing gradients through a
store, the whole train step is jit-compiled with batch sharded over the
'dp' mesh axis — GSPMD fuses the gradient all-reduce into the backward
pass over ICI, which is strictly better than a separate allreduce phase.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_data_parallel_train_step"]


def make_data_parallel_train_step(loss_fn, mesh: Mesh, optimizer_update,
                                  batch_spec=P("dp"), donate_params=True):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    loss_fn(params, batch) -> scalar; optimizer_update(grads, opt_state,
    params) -> (updates, new_opt_state) [optax-style].
    """
    replicated = NamedSharding(mesh, P())
    batch_sharding = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, batch_spec), None,
        is_leaf=lambda x: True)

    @jax.jit  # mxlint: disable=MX-DONATE001(place() device_put may alias the caller's param/opt trees - donating would delete them under the caller's binding, the transformer.make_train_step aliasing hazard)
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, new_opt_state = optimizer_update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
        return new_params, new_opt_state, loss

    def place(params, opt_state, batch):
        params = jax.device_put(params, replicated)
        opt_state = jax.device_put(opt_state, replicated)
        batch = jax.tree_util.tree_map(
            lambda b: jax.device_put(b, NamedSharding(mesh, batch_spec)),
            batch)
        return params, opt_state, batch

    step.place = place
    return step
