"""Pipeline parallelism: collective-permute microbatch pipeline.

New capability vs the reference (its closest analog is group2ctx coarse
layer placement, symbol.py:1608).  GPipe-style schedule inside
``shard_map`` over the 'pp' axis: each rank holds one stage's params;
microbatch activations flow stage→stage via ``ppermute``; ranks idle on
the bubble steps (output masked), exactly the standard TPU pipeline
recipe (scaling-book pipelining chapter).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import axis_size_compat, shard_map_compat

__all__ = ["pipeline_forward"]


def _pipeline_sharded(stage_params, microbatches, stage_fn, axis_name,
                      strip_stage_axis):
    """Run inside shard_map over 'pp'.

    stage_params: this rank's stage parameters (leading pp axis stripped).
    microbatches: (n_micro, mb_size, ...) — replicated input; rank 0
    feeds the pipeline, the last rank's outputs are collected.
    """
    npp = axis_size_compat(axis_name)
    rank = lax.axis_index(axis_name)
    if strip_stage_axis:
        # one layer per stage: drop the local (size-1) slice axis so
        # stage_fn sees per-stage params; multi-layer stages keep the
        # stacked slice and stage_fn iterates it
        stage_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    n_micro = microbatches.shape[0]
    total_steps = n_micro + npp - 1
    mb_shape = microbatches.shape[1:]

    state = jnp.zeros(mb_shape, microbatches.dtype)  # activation in flight
    outputs = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)

    def step(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if in range)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        injected = jnp.where(rank == 0,
                             microbatches[mb_idx],
                             state)
        out = stage_fn(stage_params, injected)
        # last stage emits result for microbatch t-(npp-1)
        emit_idx = t - (npp - 1)
        valid = jnp.logical_and(rank == npp - 1,
                                jnp.logical_and(emit_idx >= 0,
                                                emit_idx < n_micro))
        outputs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(emit_idx, 0, n_micro - 1), axis=0),
            lambda o: o,
            outputs)
        # shift activations to next stage
        perm = [(i, (i + 1) % npp) for i in range(npp)]
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(step, (state, outputs),
                                   jnp.arange(total_steps))
    # broadcast last-stage outputs to all pp ranks so out_specs can be
    # replicated over pp
    outputs = lax.psum(
        jnp.where(rank == npp - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def pipeline_forward(stacked_params, x, stage_fn, mesh: Mesh, n_micro=4,
                     axis_name="pp",
                     x_spec=P("dp"), param_spec=P("pp")):
    """Run ``stage_fn`` as an npp-stage pipeline.

    stacked_params: pytree whose leaves have leading axis = npp (one
    slice per stage).  x: (batch, ...) — reshaped into n_micro
    microbatches.  Returns stage-npp output with batch restored.
    """
    B = x.shape[0]
    assert B % n_micro == 0
    micro = x.reshape(n_micro, B // n_micro, *x.shape[1:])

    npp = mesh.shape[axis_name]
    leading = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(
        stacked_params)}
    assert len(leading) == 1, "stacked_params leaves must share the stage axis"
    stack = leading.pop()
    assert stack % npp == 0, \
        f"layer stack ({stack}) must divide the pp axis ({npp})"
    fn = functools.partial(_pipeline_sharded, stage_fn=stage_fn,
                           axis_name=axis_name,
                           strip_stage_axis=(stack == npp))
    param_specs = jax.tree_util.tree_map(lambda _: param_spec, stacked_params)
    mapped = shard_map_compat(
        fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P())
    out = mapped(stacked_params, micro)
    return out.reshape(B, *out.shape[2:])
