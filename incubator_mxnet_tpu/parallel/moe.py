"""Mixture-of-Experts with expert parallelism.

New capability vs the reference.  Experts are sharded over the 'ep' mesh
axis; routing uses capacity-bounded top-1/top-2 gating with dense
dispatch einsums (static shapes — the XLA-friendly Switch/GShard
formulation: dispatch/combine one-hot tensors instead of dynamic
scatter).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["moe_forward", "MoELayer", "init_moe_params"]


def init_moe_params(key, d_model, d_hidden, n_experts, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = (2.0 / d_model) ** 0.5
    scale_out = (2.0 / d_hidden) ** 0.5
    return {
        "gate": (jax.random.normal(k1, (d_model, n_experts), dtype) * 0.02),
        "w_in": (jax.random.normal(k2, (n_experts, d_model, d_hidden), dtype)
                 * scale_in),
        "w_out": (jax.random.normal(k3, (n_experts, d_hidden, d_model), dtype)
                  * scale_out),
    }


def moe_forward(params, x, capacity_factor=1.25, top_k=2):
    """x: (B, T, D) → (B, T, D) + aux load-balance loss.

    Dense dispatch: combine[b,t,e,c] one-hot tensors keep every shape
    static; with w_in/w_out sharded P('ep', ...) XLA turns the expert
    einsum into an all-to-all + local matmul over the ep axis.
    """
    B, T, D = x.shape
    E = params["gate"].shape[-1]
    S = B * T
    C = max(1, int(capacity_factor * S * top_k / E))

    tokens = x.reshape(S, D)
    logits = tokens @ params["gate"]          # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k gating with capacity via cumulative position per expert
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)      # (S, k)
    combine = jnp.zeros((S, E, C), probs.dtype)
    dispatch = jnp.zeros((S, E, C), jnp.bool_)
    for slot in range(top_k):
        e_idx = gate_idx[:, slot]                           # (S,)
        onehot = jax.nn.one_hot(e_idx, E, dtype=jnp.int32)  # (S, E)
        # rank of this token within its chosen expert's queue; the
        # (cumsum-1) must be masked BY onehot so non-selected experts
        # contribute 0, not -1 (a -1 per other expert shifted every
        # position negative and one_hot silently dropped early tokens)
        pos_in_e = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot,
                           axis=-1)                         # (S,)
        keep = pos_in_e < C
        cap_onehot = jax.nn.one_hot(jnp.where(keep, pos_in_e, C), C + 1,
                                    dtype=probs.dtype)[:, :C]
        combine = combine + gate_vals[:, slot, None, None] * \
            onehot[..., None].astype(probs.dtype) * cap_onehot[:, None, :]
        dispatch = jnp.logical_or(
            dispatch, (onehot[..., None] * cap_onehot[:, None, :]) > 0)

    expert_in = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), tokens)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in, params["w_in"]))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["w_out"])
    out = jnp.einsum("sec,ecd->sd", combine, expert_out)

    # load-balance aux loss (Switch formulation)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(gate_idx[:, 0], E, dtype=probs.dtype), axis=0)
    aux = E * jnp.sum(me * ce)
    return out.reshape(B, T, D), aux


class MoELayer:
    """Thin object wrapper used by the flagship model."""

    def __init__(self, d_model, d_hidden, n_experts, top_k=2,
                 capacity_factor=1.25):
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor

    def init(self, key, dtype=jnp.float32):
        return init_moe_params(key, self.d_model, self.d_hidden,
                               self.n_experts, dtype)

    def __call__(self, params, x):
        return moe_forward(params, x, self.capacity_factor, self.top_k)

    @staticmethod
    def partition_specs():
        return {"gate": P(None, None), "w_in": P("ep", None, "tp"),
                "w_out": P("ep", "tp", None)}
