"""Ulysses-style sequence parallelism: all-to-all head redistribution.

Alternative to ring attention for long sequences (DeepSpeed-Ulysses
pattern; see PAPERS.md): activations arrive sequence-sharded; an
all-to-all converts them to head-sharded (full sequence per device),
plain attention runs locally, and a second all-to-all restores sequence
sharding.  On TPU the all-to-alls ride ICI and cost ~2×activation size
— cheaper than ring when heads ≥ sp degree.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import axis_size_compat, shard_map_compat

__all__ = ["ulysses_attention"]


def _ulysses_sharded(q, k, v, axis_name, causal):
    """q,k,v: (B, H, T_local, D) with H full, T sharded."""
    nsp = axis_size_compat(axis_name)
    B, H, T, D = q.shape
    assert H % nsp == 0, "heads must divide sp degree for Ulysses"

    def seq2head(x):
        # (B,H,Tl,D) → split heads into nsp groups, all-to-all so each
        # rank gets H/nsp heads with the FULL sequence.  The received
        # source-rank axis must land BEFORE T (chunk-major) so that
        # merging (nsp, T) reconstructs the global sequence order —
        # head2seq then splits S the same chunk-major way, making the
        # two transforms exact inverses.
        x = x.reshape(B, nsp, H // nsp, T, D)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                           tiled=False)           # (B, H/nsp, nsp, T, D)
        return x.reshape(B, H // nsp, nsp * T, D)

    def head2seq(x):
        x = x.reshape(B, H // nsp, nsp, T, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                           tiled=False)           # (B, nsp, H/nsp, T, D)
        return x.reshape(B, H, T, D)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    scale = 1.0 / (D ** 0.5)
    logits = jnp.einsum("bhtd,bhsd->bhts", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        S = logits.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(qh.dtype)
    out = jnp.einsum("bhts,bhsd->bhtd", probs, vh)
    return head2seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name="sp", causal=False,
                      qkv_spec=P("dp", None, "sp", None)):
    fn = functools.partial(_ulysses_sharded, axis_name=axis_name,
                           causal=causal)
    mapped = shard_map_compat(
        fn, mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec)
    return mapped(q, k, v)
