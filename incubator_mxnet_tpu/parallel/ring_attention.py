"""Ring attention: exact attention over sequence-sharded inputs.

New capability vs the reference (SURVEY.md §5.7: it has none — max
sequence length bounded by one device's memory).  Design follows the
blockwise-ring formulation (Liu et al., ring attention; see PAPERS.md):
Q stays put per sp-shard; K/V blocks rotate around the sp ring via
``ppermute`` while each rank accumulates the streaming-softmax partial
(max, sum, weighted values).  ICI makes the rotation overlap with the
local attention block — the collective cost hides behind the matmuls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import axis_size_compat, shard_map_compat

__all__ = ["ring_attention", "_ring_attention_sharded"]


def _local_block(q, k, v, m_prev, l_prev, o_prev, scale, mask=None):
    """One streaming-softmax accumulation step (flash-attention algebra)."""
    logits = jnp.einsum("bhtd,bhsd->bhts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    m_cur = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    correction = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = correction * l_prev + jnp.sum(p, axis=-1)
    o_new = correction[..., None] * o_prev + \
        jnp.einsum("bhts,bhsd->bhtd", p, v.astype(p.dtype))
    return m_new, l_new, o_new


def _ring_attention_sharded(q, k, v, axis_name, causal=False):
    """Body run inside shard_map: q,k,v are (B, H, T_local, D) shards."""
    nsp = axis_size_compat(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    B, H, T, D = q.shape

    m = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, T), jnp.float32)
    o = jnp.zeros((B, H, T, D), jnp.float32)

    def step(carry, i):
        k_blk, v_blk, m_c, l_c, o_c = carry
        src_idx = (my_idx - i) % nsp  # which shard this K/V block came from
        if causal:
            q_pos = my_idx * T + jnp.arange(T)[:, None]
            k_pos = src_idx * T + jnp.arange(T)[None, :]
            mask = (q_pos >= k_pos)[None, None]
        else:
            mask = None
        m_c, l_c, o_c = _local_block(q, k_blk, v_blk, m_c, l_c, o_c, scale,
                                     mask)
        perm = [(j, (j + 1) % nsp) for j in range(nsp)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m_c, l_c, o_c), None

    (k, v, m, l, o), _ = lax.scan(step, (k, v, m, l, o), jnp.arange(nsp))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name="sp", causal=False,
                   qkv_spec=P("dp", None, "sp", None)):
    """Exact attention with sequence sharded over `axis_name`.

    q,k,v: (B, H, T, D) global arrays (sharded or not); returns same
    shape, sequence-sharded layout preserved.
    """
    fn = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                           causal=causal)
    mapped = shard_map_compat(
        fn, mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec)
    return mapped(q, k, v)
