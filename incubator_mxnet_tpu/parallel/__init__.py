"""Parallelism: SPMD over device meshes.

This layer is NEW capability relative to the reference (SURVEY.md §2.3:
MXNet 1.x has data-parallel KVStore + coarse group2ctx model parallelism;
TP/PP/SP/CP/EP are absent).  TPU-first design: a named ``Mesh`` over the
chips, sharding rules per parameter/activation, XLA collectives over ICI
inserted by GSPMD or explicitly via ``shard_map``:

* dp  — batch sharding (KVStore allreduce becomes a psum fused into the
  backward pass)
* tp  — tensor parallelism: heads/ffn sharded, psum on the row-parallel
  matmul outputs
* sp  — sequence/context parallelism: ring attention via collective
  ppermute (blockwise KV rotation), or Ulysses all-to-all head scatter
* pp  — pipeline parallelism: collective-permute microbatch pipeline
* ep  — expert parallelism: experts sharded over the mesh with
  all-to-all token routing
"""
from .mesh import (make_mesh, mesh_rules, shard_params, local_mesh,
                   leading_axis_rule)
from .ring_attention import ring_attention
from .ulysses import ulysses_attention
from .pipeline import pipeline_forward
from .moe import MoELayer, moe_forward
from .data_parallel import make_data_parallel_train_step
