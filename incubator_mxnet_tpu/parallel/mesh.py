"""Mesh construction and sharding-rule helpers."""
from __future__ import annotations

import numpy as onp

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "local_mesh", "mesh_rules", "shard_params",
           "leading_axis_rule"]

AXES = ("dp", "pp", "tp", "sp", "ep")


def make_mesh(dp=1, pp=1, tp=1, sp=1, ep=1, devices=None) -> Mesh:
    """Build a named mesh over the available devices.

    Axis order is chosen so that tp (highest-bandwidth collectives) maps
    to the innermost/nearest chips on a TPU slice — the standard layout
    recipe: put the axis with the chattiest collectives on the fastest
    ICI ring.
    """
    devices = devices if devices is not None else jax.devices()
    n = dp * pp * tp * sp * ep
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = onp.asarray(devices[:n]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))


def local_mesh(**kwargs) -> Mesh:
    return make_mesh(**kwargs)


def mesh_rules(kind: str):
    """PartitionSpec rules for common tensors in a transformer stack."""
    rules = {
        # params
        "embed": P(None, "tp"),
        "attn_qkv": P(None, "tp"),           # (d_model, heads*dh) col-parallel
        "attn_out": P("tp", None),           # row-parallel
        "mlp_in": P(None, "tp"),
        "mlp_out": P("tp", None),
        "moe_experts": P("ep", None, None),  # (experts, d_in, d_out)
        "norm": P(None),
        # activations
        "tokens": P("dp", "sp"),
        "activation": P("dp", "sp", None),
        "logits": P("dp", "sp", "tp"),
    }
    return rules[kind]


def leading_axis_rule(mesh: Mesh, axis: str = "dp"):
    """``rule_fn(name, leaf) -> PartitionSpec`` sharding the leading
    dimension over ``axis`` whenever it divides evenly, replicating
    otherwise — the standard fully-sharded-data-parallel placement for
    parameter trees.

    Works for both :func:`shard_params` (leaf = array) and
    ``AsyncCheckpointManager.reshard_restore`` (leaf =
    ``jax.ShapeDtypeStruct``): only ``.shape`` is consulted, so one rule
    serves save-side placement and restore-side re-layout across mesh
    shapes.
    """
    n = int(mesh.shape[axis])

    def rule(name, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if shape and n > 1 and shape[0] % n == 0:
            return P(axis, *([None] * (len(shape) - 1)))
        return P()

    return rule


def shard_params(params, mesh: Mesh, rule_fn):
    """Place a parameter pytree onto the mesh.

    rule_fn(path, leaf) -> PartitionSpec; used by the flagship model and
    by ``dryrun_multichip``.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for path, leaf in flat:
        spec = rule_fn(jax.tree_util.keystr(path), leaf)
        placed.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, placed)
