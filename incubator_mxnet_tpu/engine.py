"""Dependency engine — async scheduling with read/write variable ordering.

TPU-native re-design of the reference dependency engine (src/engine/,
include/mxnet/engine.h:117-318).  On GPU the reference engine is the whole
async story: every op is pushed with const/mutable vars and executed by
per-device worker pools (threaded_engine_perdevice.cc:47-158).  On TPU the
*device-side* asynchrony is already provided by PJRT's async dispatch —
XLA executables launch asynchronously and `jax.Array`s are futures.  What
remains engine-shaped, and what this module provides:

* ``Var`` with a version counter (reference include/mxnet/engine.h:44-61) so
  mutation ordering over shared buffers is observable/testable.
* ``push(fn, const_vars, mutable_vars)`` honouring read/write dependency
  ordering — reads of a version may proceed concurrently; writes serialize
  (reference threaded_engine.h:101-229 ``VersionedVarBlock`` queues).
* Exception capture on vars, rethrown at ``wait_for_var``/``wait_for_all``
  (reference threaded_engine.cc:422-522) — the async-error contract that
  ``NDArray.asnumpy`` relies on.
* Two implementations selected by ``MXNET_ENGINE_TYPE`` (reference
  src/engine/engine.cc:33-45): ``NaiveEngine`` (synchronous, for
  debugging) and ``ThreadedEngine`` (worker pool).  Device kernels do NOT
  run on these threads — they only sequence host-side closures (data
  pipeline stages, checkpoint IO, KVStore server logic); device compute is
  sequenced by JAX program order.
"""
from __future__ import annotations

import threading
import traceback
from collections import deque

from . import fault
from .analysis import race as _race
from .base import get_env
from .locks import named_condition, named_lock

__all__ = ["Var", "Engine", "NaiveEngine", "ThreadedEngine", "get_engine", "set_engine"]


class Var:
    """A scheduling variable with a version counter.

    Reference: engine::Var (include/mxnet/engine.h:44-61) — ``version()``
    bumps on each write completion, which is how the reference detects
    stale reads; we keep the same contract.
    """

    __slots__ = ("_lock", "_version", "_pending_writes", "_pending_reads",
                 "_queue", "_exc", "name")

    def __init__(self, name: str = ""):
        self._lock = named_lock("engine.var")
        self._version = 0
        self._pending_writes = 0
        self._pending_reads = 0
        self._queue: deque = deque()  # waiting (op, is_write) entries
        self._exc = None
        self.name = name

    @property
    def version(self) -> int:
        return self._version

    def __repr__(self):
        return f"Var({self.name or hex(id(self))}, v{self._version})"


class _OpBlock:
    __slots__ = ("fn", "const_vars", "mutable_vars", "wait_count", "lock",
                 "done", "exc", "name")

    def __init__(self, fn, const_vars, mutable_vars, name):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.wait_count = 0
        self.lock = named_lock("engine.op")
        self.done = threading.Event()
        self.exc = None
        self.name = name


class Engine:
    """Abstract engine interface (reference include/mxnet/engine.h:117)."""

    def new_variable(self, name: str = "") -> Var:
        return Var(name)

    def push(self, fn, const_vars=(), mutable_vars=(), name="op"):
        raise NotImplementedError

    def push_sync(self, fn, const_vars=(), mutable_vars=(), name="op"):
        op = self.push(fn, const_vars, mutable_vars, name)
        op.done.wait()
        if op.exc is not None:
            raise op.exc
        return op

    def wait_for_var(self, var: Var):
        raise NotImplementedError

    def wait_for_all(self):
        raise NotImplementedError

    def stop(self):
        """Join any worker threads.  The engine is done after this —
        callers build a fresh one via ``reset_engine()`` if needed."""

    def throw_pending(self, var: Var):
        with var._lock:
            exc, var._exc = var._exc, None
        if exc is not None:
            raise exc


class NaiveEngine(Engine):
    """Synchronous engine: run on push (reference naive_engine.cc:51)."""

    def push(self, fn, const_vars=(), mutable_vars=(), name="op"):
        fault.inject("engine.push", detail=name)
        op = _OpBlock(fn, tuple(const_vars), tuple(mutable_vars), name)
        rec = (_race.begin(name, op.const_vars, op.mutable_vars)
               if _race.enabled else None)
        try:
            fn()
        except Exception as e:  # mxlint: allow-broad-except(engine boundary: banked sticky on the op and its vars, rethrown at wait_for_var)
            op.exc = e
            for v in op.mutable_vars:
                v._exc = e
        except BaseException:
            # KeyboardInterrupt/SystemExit propagate, but the race
            # record must still come off the thread-local stack or
            # every later access on this thread leaks into it
            if rec is not None:
                _race.finish(rec, collect=True)
                rec = None
            raise
        for v in op.mutable_vars:
            v._version += 1
        op.done.set()
        if rec is not None:
            # synchronous engine: a declaration violation surfaces at
            # the push that committed it (collect=False raises)
            _race.finish(rec, collect=False)
        return op

    def wait_for_var(self, var):
        self.throw_pending(var)
        if _race.enabled:
            # violations banked on the BaseException push path drain
            # here, not at some unrelated later engine's wait
            _race.raise_pending()

    def wait_for_all(self):
        if _race.enabled:
            _race.raise_pending()


class ThreadedEngine(Engine):
    """Worker-pool engine with RW dependency queues.

    Re-implements the scheduling core of threaded_engine.h:101-229:
    each Var keeps a FIFO of waiting ops; concurrent readers of the same
    version run in parallel, writers are exclusive.  Host closures only.
    """

    def __init__(self, num_workers: int | None = None):
        self._num_workers = num_workers or get_env("MXNET_CPU_WORKER_NTHREADS", 4, int)
        self._ready: deque = deque()
        self._cv = named_condition("engine.ready")
        self._inflight = 0
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"mxtpu-engine-{i}")
            for i in range(self._num_workers)
        ]
        for t in self._threads:
            t.start()

    # -- dependency bookkeeping ------------------------------------------
    def push(self, fn, const_vars=(), mutable_vars=(), name="op"):
        fault.inject("engine.push", detail=name)
        const_vars = tuple(const_vars)
        mutable_vars = tuple(mutable_vars)
        dup = set(const_vars) & set(mutable_vars)
        if dup:
            const_vars = tuple(v for v in const_vars if v not in dup)
        if _race.enabled:
            # violations are banked and rethrown at wait_for_* (the
            # sticky-exception contract); flag-off adds no allocation
            fn = _race.wrap(fn, name, const_vars, mutable_vars)
        op = _OpBlock(fn, const_vars, mutable_vars, name)
        with self._cv:
            self._inflight += 1
        blocked = 0
        for v in const_vars:
            with v._lock:
                if v._pending_writes > 0 or v._queue:
                    v._queue.append((op, False))
                    blocked += 1
                else:
                    v._pending_reads += 1
        for v in mutable_vars:
            with v._lock:
                if v._pending_writes > 0 or v._pending_reads > 0 or v._queue:
                    v._queue.append((op, True))
                    blocked += 1
                else:
                    v._pending_writes += 1
        with op.lock:
            op.wait_count += blocked
            ready = op.wait_count == 0 and blocked == 0
        if ready:
            self._enqueue(op)
        else:
            # account for deps that resolved between our scan and now
            self._maybe_ready(op, delta=0)
        return op

    def _maybe_ready(self, op, delta):
        with op.lock:
            op.wait_count -= delta
            ready = op.wait_count == 0
        if ready and delta != 0:
            self._enqueue(op)

    def _enqueue(self, op):
        with self._cv:
            self._ready.append(op)
            self._cv.notify()

    def _release_var(self, v: Var, was_write: bool, exc):
        to_wake = []
        with v._lock:
            if was_write:
                v._pending_writes -= 1
                v._version += 1
                if exc is not None:
                    v._exc = exc
            else:
                v._pending_reads -= 1
            # drain queue head: a run of reads, or one write
            while v._queue:
                op, is_write = v._queue[0]
                if is_write:
                    if v._pending_reads == 0 and v._pending_writes == 0:
                        v._queue.popleft()
                        v._pending_writes += 1
                        to_wake.append(op)
                    break
                if v._pending_writes > 0:
                    break
                v._queue.popleft()
                v._pending_reads += 1
                to_wake.append(op)
        for op in to_wake:
            self._maybe_ready(op, delta=1)

    def _worker_loop(self):
        while True:
            with self._cv:
                while not self._ready and not self._shutdown:
                    self._cv.wait()
                if self._shutdown and not self._ready:
                    return
                op = self._ready.popleft()
            exc = None
            try:
                op.fn()
            except Exception as e:  # mxlint: allow-broad-except(engine boundary: banked sticky on the op and its vars, rethrown at wait_for_var)
                exc = e
                exc._engine_traceback = traceback.format_exc()
                op.exc = e
            for v in op.const_vars:
                self._release_var(v, was_write=False, exc=None)
            for v in op.mutable_vars:
                self._release_var(v, was_write=True, exc=exc)
            op.done.set()
            with self._cv:
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()

    def stop(self):
        """Drain the ready queue, then join every worker.  Workers exit
        only once ``_ready`` is empty, so queued ops still run."""
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = [t for t in self._threads if t.is_alive()]

    # -- waits ------------------------------------------------------------
    def wait_for_var(self, var: Var):
        probe = self.push(lambda: None, const_vars=(var,), name="wait_for_var")
        probe.done.wait()
        self.throw_pending(var)
        if _race.enabled:
            _race.raise_pending()

    def wait_for_all(self):
        with self._cv:
            while self._inflight:
                self._cv.wait()
        if _race.enabled:
            _race.raise_pending()


class NativeEngine(Engine):
    """Dependency engine backed by the C++ runtime (src/engine.cc).

    Same semantics as ThreadedEngine — RW var queues, version bump on
    write, sticky exception propagation (reference
    threaded_engine.cc:422-522) — but scheduling, worker threads and
    dependency bookkeeping run natively; Python closures are invoked via
    a single ctypes trampoline. Selected with
    ``MXNET_ENGINE_TYPE=NativeEngine``.
    """

    class _Var:
        __slots__ = ("handle", "name", "_version", "_exc", "_engine",
                     "__weakref__")

        def __init__(self, handle, name, engine):
            self.handle = handle
            self.name = name
            self._version = 0
            self._exc = None
            self._engine = engine

        def __del__(self):
            # ordered teardown: the native side frees the var once all
            # pending ops on it drain (engine.cc DeleteVar)
            eng = self._engine
            if self.handle is not None and eng is not None \
                    and getattr(eng, "_lib", None) is not None:
                try:
                    eng._lib.MXTEngineDeleteVar(eng._h, self.handle)
                except Exception:  # mxlint: allow-broad-except(interpreter teardown: the native lib may already be unloaded)
                    pass
                self.handle = None

    def __init__(self, num_workers: int | None = None):
        from . import native
        if not native.available():
            raise RuntimeError("native runtime library not built")
        self._native = native
        self._lib = native.lib
        import ctypes
        self._ctypes = ctypes
        self._libc = ctypes.CDLL(None)
        self._libc.strdup.restype = ctypes.c_void_p
        self._libc.strdup.argtypes = [ctypes.c_char_p]
        h = ctypes.c_void_p()
        nw = num_workers or get_env("MXNET_CPU_WORKER_NTHREADS", 0, int)
        native.check_call(self._lib.MXTEngineCreate(nw, ctypes.byref(h)))
        self._h = h
        self._ops: dict[int, object] = {}
        self._ops_lock = named_lock("engine.ops")
        self._next_token = [1]

        libc = self._libc

        @native.ENGINE_FN
        def _trampoline(ctx, upstream_err, err_out):
            token = int(ctx)
            with self._ops_lock:
                fn, done_evt, holder = self._ops.pop(token)
            try:
                if upstream_err is not None:
                    # op skipped: an input var carries a sticky exception
                    # (engine.cc WorkerLoop); release waiters, record it
                    holder.append(RuntimeError(
                        upstream_err.decode("utf-8", "replace")))
                else:
                    fn()
            except Exception as e:  # mxlint: allow-broad-except(engine boundary: marshalled into the C error slot and rethrown at the next wait)
                msg = f"{type(e).__name__}: {e}"
                err_out[0] = libc.strdup(msg.encode("utf-8", "replace"))
                holder.append(e)
            finally:
                done_evt.set()

        self._trampoline = _trampoline  # keep alive for the engine lifetime

    def new_variable(self, name: str = ""):
        h = self._ctypes.c_void_p()
        self._native.check_call(
            self._lib.MXTEngineNewVar(self._h, self._ctypes.byref(h)))
        return NativeEngine._Var(h, name, self)

    def _var_array(self, vars_):
        arr = (self._ctypes.c_void_p * len(vars_))()
        for i, v in enumerate(vars_):
            arr[i] = v.handle
        return arr

    def push(self, fn, const_vars=(), mutable_vars=(), name="op", priority=0):
        fault.inject("engine.push", detail=name)
        const_vars = tuple(const_vars)
        mutable_vars = tuple(mutable_vars)
        dup = set(id(v) for v in const_vars) & set(id(v) for v in mutable_vars)
        if dup:
            const_vars = tuple(v for v in const_vars if id(v) not in dup)
        if _race.enabled:
            # bump python-side versions at op completion (inside the
            # C-serialized slot), not at push: a push-time bump makes a
            # correctly-declared concurrent reader look like it saw a
            # write-after-read hazard
            inner, bump_vars = fn, mutable_vars

            def _run_and_bump():
                try:
                    inner()
                finally:
                    for v in bump_vars:
                        v._version += 1
            fn = _race.wrap(_run_and_bump, name, const_vars, mutable_vars)
        done_evt = threading.Event()
        holder: list = []
        with self._ops_lock:
            token = self._next_token[0]
            self._next_token[0] += 1
            self._ops[token] = (fn, done_evt, holder)
        if not _race.enabled:
            for v in mutable_vars:
                v._version += 1
        self._native.check_call(self._lib.MXTEnginePush(
            self._h, self._trampoline, self._ctypes.c_void_p(token),
            self._var_array(const_vars), len(const_vars),
            self._var_array(mutable_vars), len(mutable_vars), priority))

        class _Handle:
            done = done_evt
            _holder = holder

            @property
            def exc(self):
                return holder[0] if holder else None
        return _Handle()

    def push_sync(self, fn, const_vars=(), mutable_vars=(), name="op"):
        op = self.push(fn, const_vars, mutable_vars, name)
        op.done.wait()
        if op.exc is not None:
            raise op.exc
        return op

    def wait_for_var(self, var):
        rc = self._lib.MXTEngineWaitForVar(self._h, var.handle)
        if rc != 0:
            msg = self._lib.MXTGetLastError().decode("utf-8", "replace")
            raise RuntimeError(msg)
        if _race.enabled:
            _race.raise_pending()

    def wait_for_all(self):
        rc = self._lib.MXTEngineWaitAll(self._h)
        if rc != 0:
            msg = self._lib.MXTGetLastError().decode("utf-8", "replace")
            raise RuntimeError(msg)
        if _race.enabled:
            _race.raise_pending()

    def throw_pending(self, var):
        self.wait_for_var(var)


_engine_lock = named_lock("engine.singleton")
_engine: Engine | None = None


def get_engine() -> Engine:
    global _engine
    with _engine_lock:
        if _engine is None:
            kind = get_env("MXNET_ENGINE_TYPE", "ThreadedEngine")
            if kind == "NaiveEngine":
                _engine = NaiveEngine()
            elif kind == "NativeEngine":
                _engine = NativeEngine()
            else:
                _engine = ThreadedEngine()
        return _engine


def set_engine(engine: Engine):
    global _engine
    with _engine_lock:
        _engine = engine


def reset_engine():
    """Drop the singleton so the next use builds a fresh engine — called
    from the after-fork handler (reference initialize.h fork handlers):
    a forked child must not drive the parent's worker threads or hold
    its queue locks."""
    global _engine
    # deliberately no lock: after fork the old lock may be held by a
    # thread that no longer exists in the child
    _engine = None
