"""Exception hierarchy (reference python/mxnet/error.py).

MXNetError is the base carried across the C ABI (rc -1 +
MXTGetLastError).  Subclasses dual-inherit the matching python builtin
(reference error.py does the same) so both ``except mx.error.ValueError``
and plain ``except ValueError`` catch them.  The native ``check_call``
and FFI error paths dispatch messages prefixed "Kind: ..." onto the
registered class via :func:`get_error_class`.
"""
import builtins as _bi

from .base import MXNetError

__all__ = ["MXNetError", "InternalError", "IndexError", "ValueError",
           "TypeError", "AttributeError", "NotImplementedError",
           "PSTimeoutError", "PSConnectionError", "CheckpointCorruptError",
           "CheckpointWriteError", "WorkerEvictedError", "ReshardError",
           "ReplicaUnavailableError", "FleetDrainingError",
           "ModelEvictedError",
           "RouterLeaseError", "RouterForwardError",
           "SessionExpiredError", "SessionLostError",
           "EngineRaceError", "RecompileStormError", "GraphLintError",
           "LockOrderError", "ShardLintError",
           "register_error", "get_error_class"]

_ERROR_REGISTRY = {}


def register_error(cls=None, name=None):
    """Register an error class by name (reference error.py:register)."""
    def deco(c):
        _ERROR_REGISTRY[name or c.__name__] = c
        return c
    return deco(cls) if cls is not None else deco


def get_error_class(kind, default=MXNetError):
    """Resolve a registered error kind ("ValueError", ...) to its class."""
    return _ERROR_REGISTRY.get(kind, default)


@register_error
class InternalError(MXNetError):
    """An internal invariant was violated."""


@register_error
class IndexError(MXNetError, _bi.IndexError):
    """Index out of range (also catchable as builtin IndexError)."""


@register_error
class ValueError(MXNetError, _bi.ValueError):
    """Invalid argument value (also catchable as builtin ValueError)."""


@register_error
class TypeError(MXNetError, _bi.TypeError):
    """Invalid argument type (also catchable as builtin TypeError)."""


@register_error
class AttributeError(MXNetError, _bi.AttributeError):
    """Attribute not found (also catchable as builtin AttributeError)."""


@register_error
class NotImplementedError(MXNetError, _bi.NotImplementedError):
    """Feature not implemented."""


@register_error
class PSTimeoutError(MXNetError, _bi.TimeoutError):
    """A parameter-server operation did not complete within its budget
    (bounded sync-pull/barrier wait, or client retries exhausted).  The
    message names the stalled command/key/round so a hung job is
    diagnosable from the traceback alone.  Also catchable as builtin
    ``TimeoutError``."""


@register_error
class PSConnectionError(MXNetError, _bi.ConnectionError):
    """The parameter-server transport failed and could not be
    re-established (reconnect attempts exhausted).  Also catchable as
    builtin ``ConnectionError``."""


@register_error
class CheckpointCorruptError(MXNetError):
    """A checkpoint shard failed integrity verification (CRC mismatch,
    truncated file, or missing shards) — the checkpoint must not load
    silently."""


@register_error
class CheckpointWriteError(MXNetError, _bi.RuntimeError):
    """The async checkpoint writer thread failed.  The exception is
    banked on the manager and re-raised (as this type, chained to the
    original) at the next ``save()``/``wait()`` — a silently-failing
    checkpoint loop must not run for hours believing it has durable
    state.  Also catchable as builtin ``RuntimeError``."""


@register_error
class WorkerEvictedError(MXNetError):
    """This worker was evicted from the parameter-server membership
    table (it missed its ``MXNET_KVSTORE_DEAD_AFTER`` heartbeat budget,
    or the fleet was rebalanced without it).  The elastic trainer
    checkpoints on this notice; the worker must ``join`` again (and
    bootstrap by pulling current weights) before pushing more work."""


@register_error
class ReshardError(MXNetError, _bi.ValueError):
    """A checkpoint could not be restored onto the requested mesh /
    sharding: a name in the target tree has no entry in the per-shard
    index, the recorded global shape or dtype conflicts with the target
    spec, or the placement rule produced a spec the mesh cannot carry.
    Integrity damage (CRC mismatch, missing shard files) is NOT this
    error — that stays :class:`CheckpointCorruptError` so newest-first
    fallback applies.  Also catchable as builtin ``ValueError``."""


@register_error
class ReplicaUnavailableError(MXNetError, _bi.ConnectionError):
    """A serving-fleet request could not be placed on any replica: no
    replica is in the ``ready`` state (all warming, unhealthy, or
    dead), or the targeted replica refused the connection.  The fleet
    router answers 503 with ``Retry-After`` — the condition is
    transient (replicas re-warm, probes re-admit).  Also catchable as
    builtin ``ConnectionError`` so failover/retry layers treat it like
    a real refused socket."""


@register_error
class FleetDrainingError(MXNetError):
    """Every live replica in the serving fleet is draining — the fleet
    is shutting down (or mid-roll with nothing re-admitted yet) and
    admits no new work.  Answered as 503 with ``Retry-After``; a
    client must never hang on a fleet that will not serve it."""


@register_error
class ModelEvictedError(MXNetError, _bi.ConnectionError):
    """A request named a model the autoscaler evicted from every
    replica (LRU bin-packing under the per-replica HBM budget, or
    idle scale-to-zero) and the on-demand reload could not place it —
    every replica's budget is held by busier models and the fleet is
    at its replica ceiling (``serving/autoscaler.py``).  Answered as
    503 with ``Retry-After``: the condition clears when load recedes
    or capacity grows, so clients should back off and retry.  Also
    catchable as builtin ``ConnectionError`` so generic failover
    layers treat it as a retryable placement failure, not a 500."""


@register_error
class RouterLeaseError(MXNetError, _bi.ConnectionError):
    """A router's lease on the shared HA membership store could not be
    acquired, renewed, or trusted (``serving/routerha.py``): the store
    is unreachable, the lease expired while the router was wedged, or
    a peer named by a forwarded request holds no live lease.  Also
    catchable as builtin ``ConnectionError`` so retry/failover layers
    treat it as transient — leases re-acquire on the next beat.
    Answered as 503 with ``Retry-After`` by the router front end."""


@register_error
class RouterForwardError(MXNetError):
    """A mis-hashed session request exhausted its
    ``X-MXNET-ROUTER`` forward-hop budget
    (``MXNET_SERVING_ROUTER_FORWARD_HOPS``) without reaching the
    session's owning router — a routing loop (stale membership views
    disagreeing about ring ownership) or a peer list naming routers
    that no longer exist.  The hop cap turns an infinite forward loop
    into this typed error (HTTP 508); the client should retry after
    the membership view converges (one lease TTL)."""


@register_error
class SessionExpiredError(MXNetError):
    """A serving session was evicted by policy — it ran past its idle
    TTL (``MXNET_SERVING_SESSION_TTL_S``), was the least-recently-used
    session when the per-model cap (``MXNET_SERVING_SESSION_MAX``)
    forced an eviction, or was closed while a step was still queued.
    The session's carry is gone on purpose; the client must create a
    new session.  Answered as HTTP 410 (Gone) by the serving front
    ends — retrying the same session id can never succeed."""


@register_error
class SessionLostError(MXNetError):
    """A stateful serving session's carry could not be recovered: its
    replica died (or drained away) and no valid CRC-verified snapshot
    exists to migrate the session from (``serving/sessions.py``).  This
    is the failover contract's *typed* failure arm — a dead session
    must surface as this error, never as a hang and never as a stream
    silently restarting from scratch.  Answered as HTTP 410 (Gone);
    the client must create a new session."""


@register_error
class GraphLintError(MXNetError):
    """The IR linter (``analysis/graphlint.py``) found violations in a
    graph whose caller demanded a clean bill
    (``MXNET_EXPORT_GRAPHLINT=raise`` at export, or the graphlint CI
    stage).  The message lists the findings with rule ids and the
    traced source lines."""


@register_error
class MemLintError(GraphLintError):
    """The memory analyzer (``analysis/memlint.py``) found violations
    under ``MXNET_GRAPH_MEMLINT=strict`` — an undonated buffer at a
    surface that contracts to donate (ML-DONATE001), or a peak-HBM
    estimate over its budget (ML-PEAK001).  Subclasses
    :class:`GraphLintError` so callers gating on "the IR analysis
    failed the build" catch both."""


@register_error
class ShardLintError(GraphLintError):
    """The sharding analyzer (``analysis/shardlint.py``) found
    violations under ``MXNET_GRAPH_SHARDLINT=strict`` — a per-shard
    peak over the chip budget (SL-SHARD-PEAK001), incompatible declared
    shardings on one value (SL-RESHARD001), a large fully replicated
    weight (SL-REPL001), a spec naming a missing mesh axis (SL-SPEC001),
    or a donated input resharded before reuse (SL-DONATE001).
    Subclasses :class:`GraphLintError` so callers gating on "the IR
    analysis failed the build" catch all three analyzers."""


@register_error
class RecompileStormError(MXNetError):
    """A jitted entry point exceeded its per-site XLA compile budget
    under ``MXNET_RECOMPILE_SENTINEL=raise`` (``analysis/recompile.py``).
    The message names the site and WHAT changed between the last two
    compile signatures (a varying batch dim, a per-call static arg, a
    dropped cache) so the churn is fixable from the traceback alone."""


@register_error
class EngineRaceError(MXNetError):
    """An engine op's actual NDArray accesses disagreed with its
    declared ``const_vars``/``mutable_vars`` (undeclared write,
    undeclared read, or a write-after-read version hazard), detected
    under ``MXNET_ENGINE_RACE_CHECK=1`` (``analysis/race.py``).  The
    message names the op and the variable so the missing declaration is
    findable from the traceback alone."""


@register_error
class LockOrderError(MXNetError, _bi.RuntimeError):
    """The runtime lock witness (``MXNET_LOCK_WITNESS=1``,
    ``analysis/lockwitness.py``) observed a cycle in the global
    acquisition-order graph over named locks — two code paths acquire
    the same locks in opposite orders, i.e. a latent deadlock.  Banked
    at the offending acquire and rethrown from
    ``lockwitness.check()``-style boundaries, never from inside the
    victim's ``acquire`` (the acquire itself stays well-formed).  The
    message carries the cycle (``a -> b -> a``) and the acquiring
    threads so the ordering fix is findable from the error alone."""
