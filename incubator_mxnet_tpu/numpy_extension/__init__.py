"""``mx.npx``: operators beyond the NumPy standard
(reference python/mxnet/numpy_extension/)."""
from __future__ import annotations

from ..ndarray import NDArray
from ..ops.registry import invoke
from ..util import is_np_array, set_np, reset_np  # noqa: F401


def _op(name):
    def fn(*args, **kwargs):
        return invoke(name, *args, **kwargs)

    fn.__name__ = name
    return fn


softmax = _op("softmax")
log_softmax = _op("log_softmax")
relu = _op("relu")
sigmoid = _op("sigmoid")
activation = _op("Activation")
batch_norm = _op("BatchNorm")
layer_norm = _op("LayerNorm")
group_norm = _op("GroupNorm")
fully_connected = _op("FullyConnected")
convolution = _op("Convolution")
pooling = _op("Pooling")
one_hot = _op("one_hot")
pick = _op("pick")
topk = _op("topk")
embedding = _op("Embedding")
gather_nd = _op("gather_nd")
rnn = _op("RNN")
sequence_mask = _op("SequenceMask")
smooth_l1 = _op("smooth_l1")
gelu = _op("gelu")
leaky_relu = _op("leaky_relu")


def reshape_like(lhs, rhs):
    return invoke("reshape_like", lhs, rhs)


def waitall():
    from .. import ndarray as nd
    nd.waitall()


def load(fname):
    from .. import ndarray as nd
    return nd.load(fname)


def save(fname, data):
    from .. import ndarray as nd
    return nd.save(fname, data)


def set_np_shape(active=True):
    return active


class cpu:  # noqa: N801 — reference exposes npx.cpu()/npx.gpu()
    def __new__(cls, device_id=0):
        from ..context import cpu as _cpu
        return _cpu(device_id)


class gpu:  # noqa: N801
    def __new__(cls, device_id=0):
        from ..context import gpu as _gpu
        return _gpu(device_id)


def num_gpus():
    from ..context import num_gpus as _n
    return _n()
