"""``mx.npx``: operators beyond the NumPy standard
(reference python/mxnet/numpy_extension/)."""
from __future__ import annotations

from ..ndarray import NDArray
from ..ops.registry import invoke
from ..util import is_np_array, set_np, reset_np  # noqa: F401


def _op(name):
    def fn(*args, **kwargs):
        return invoke(name, *args, **kwargs)

    fn.__name__ = name
    return fn


softmax = _op("softmax")
log_softmax = _op("log_softmax")
relu = _op("relu")
sigmoid = _op("sigmoid")
activation = _op("Activation")
batch_norm = _op("BatchNorm")
layer_norm = _op("LayerNorm")
group_norm = _op("GroupNorm")
fully_connected = _op("FullyConnected")
convolution = _op("Convolution")
pooling = _op("Pooling")
one_hot = _op("one_hot")
pick = _op("pick")
topk = _op("topk")
embedding = _op("Embedding")
gather_nd = _op("gather_nd")
rnn = _op("RNN")
sequence_mask = _op("SequenceMask")
smooth_l1 = _op("smooth_l1")
gelu = _op("gelu")
leaky_relu = _op("leaky_relu")


batch_dot = _op("batch_dot")
scatter_nd = _op("scatter_nd")
index_add = _op("index_add_nd")
index_update = _op("index_update_nd")


def reshape(a, newshape, reverse=False, order="C"):
    """npx.reshape with the numpy-extension special codes (reference
    src/operator/numpy/np_matrix_op.cc:199 `_npx_reshape`: -1 infer,
    -2 copy dim, -3 skip size-1 dim, -4 copy rest, -5 merge two,
    -6 split)."""
    from ..ops.shape_ops import npx_reshape_shape
    if order != "C":
        raise NotImplementedError("npx.reshape supports order='C' only")
    if isinstance(newshape, int):
        newshape = (newshape,)
    resolved = npx_reshape_shape(a.shape, newshape, reverse=reverse)
    return invoke("reshape", a, shape=resolved)


def constraint_check(data, msg="Constraint violated"):
    """Eager all-true assertion (reference `_npx_constraint_check`,
    src/operator/numpy_extension/npx_constraint_check.cc): raises
    ``ValueError(msg)`` if any element is falsy, else returns a scalar
    True array.  Data-dependent by construction, so it runs host-side
    like the reference's kernel-side CHECK."""
    import numpy as onp
    arr = data.asnumpy() if hasattr(data, "asnumpy") else onp.asarray(data)
    if not bool(arr.all()):
        raise ValueError(msg)
    from .. import ndarray as nd
    return nd.array(onp.asarray(True))


def nonzero(a):
    """Indices of nonzero elements as an (N, ndim) int32 array
    (reference `_npx_nonzero`, src/operator/numpy/np_nonzero_op.cc,
    emits int64; JAX runs x64-disabled so int32 is the index dtype
    here).  Output shape is data-dependent, so this is an eager host op
    there and here."""
    import numpy as onp
    arr = a.asnumpy() if hasattr(a, "asnumpy") else onp.asarray(a)
    # reference emits int64; JAX runs x64-disabled so int32 is the
    # widest index dtype here (shapes stay < 2^31 on one chip)
    idx = onp.transpose(onp.nonzero(arr)).astype(onp.int32)
    from .. import ndarray as nd
    return nd.array(idx, dtype="int32")


def reshape_like(lhs, rhs):
    return invoke("reshape_like", lhs, rhs)


def waitall():
    from .. import ndarray as nd
    nd.waitall()


def load(fname):
    from .. import ndarray as nd
    return nd.load(fname)


def save(fname, data):
    from .. import ndarray as nd
    return nd.save(fname, data)


def set_np_shape(active=True):
    return active


class cpu:  # noqa: N801 — reference exposes npx.cpu()/npx.gpu()
    def __new__(cls, device_id=0):
        from ..context import cpu as _cpu
        return _cpu(device_id)


class gpu:  # noqa: N801
    def __new__(cls, device_id=0):
        from ..context import gpu as _gpu
        return _gpu(device_id)


def num_gpus():
    from ..context import num_gpus as _n
    return _n()
