"""Weight initializers (reference python/mxnet/initializer.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .base import registry
from . import random as _random

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "InitDesc", "Load", "FusedRNN", "Mixed", "register", "create"]

_reg = registry("initializer")
register = _reg.register
create = _reg.create


class Initializer:
    """Base initializer; callable on (name, NDArray) or just NDArray."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr=None):
        if arr is None:
            name, arr = "", name
        name = getattr(name, "name", name) or ""
        if name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith(("beta", "bias", "moving_mean", "running_mean")):
            self._init_zero(arr)
        elif name.endswith(("moving_var", "running_var")):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def init_array(self, arr):
        self._init_weight("", arr)

    def _init_zero(self, arr):
        arr._set_data(jnp.zeros(arr.shape, arr.data.dtype))

    def _init_one(self, arr):
        arr._set_data(jnp.ones(arr.shape, arr.data.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"

    def dumps(self):
        import json
        return json.dumps([type(self).__name__.lower(), self._kwargs])


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._set_data(jnp.full(arr.shape, self.value, arr.data.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        key = _random.next_key()
        arr._set_data(jax.random.uniform(
            key, arr.shape, jnp.float32, -self.scale, self.scale
        ).astype(arr.data.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        key = _random.next_key()
        arr._set_data((self.sigma * jax.random.normal(
            key, arr.shape, jnp.float32)).astype(arr.data.dtype))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        key = _random.next_key()
        nout = arr.shape[0]
        nin = int(jnp.prod(jnp.asarray(arr.shape[1:])))
        a = jax.random.normal(key, (nout, nin), jnp.float32)
        q, r = jnp.linalg.qr(a if nout >= nin else a.T)
        q = q if nout >= nin else q.T
        q = q * jnp.sign(jnp.diagonal(r))[..., None] if nout >= nin else q
        arr._set_data((self.scale * q[:nout, :nin]).reshape(arr.shape).astype(arr.data.dtype))


@register
class Xavier(Initializer):
    """Glorot init (reference initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            fan_in, fan_out = shape[0], shape[0]
        else:
            for s in shape[2:]:
                hw_scale *= s
            fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / max(factor, 1e-12))
        key = _random.next_key()
        if self.rnd_type == "uniform":
            val = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            val = scale * jax.random.normal(key, shape, jnp.float32)
        arr._set_data(val.astype(arr.data.dtype))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        import numpy as onp
        shape = arr.shape
        weight = onp.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight).astype(arr.data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        import numpy as onp
        b = onp.zeros(arr.shape, "float32")
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias
        arr._set_data(jnp.asarray(b).astype(arr.data.dtype))


_reg.alias("zeros")(Zero)
_reg.alias("ones")(One)
_reg.alias("gaussian")(Normal)


class Mixed:
    """Patterned initializer dispatch (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        self.map = [(re.compile(p), init) for p, init in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")


class InitDesc(str):
    """Initialization-pattern descriptor (reference initializer.py:36):
    a parameter name carrying its symbol attrs and the global fallback
    initializer — passed to initializers on the symbolic init path."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Load:
    """Initialize parameters from a saved ``.params`` file or dict
    (reference initializer.py:318); ``arg:``/``aux:`` prefixes are
    dropped, unmatched names fall back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as _nd_load
            param = _nd_load(param)
        if not isinstance(param, dict):
            raise TypeError("Load needs a .params path or a name->NDArray "
                            "dict")
        self.param = {}
        for name, arr in param.items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[key] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr=None):
        if arr is None:
            name, arr = "", name
        name = getattr(name, "name", name) or str(name)
        if name in self.param:
            src = self.param[name]
            if tuple(arr.shape) != tuple(src.shape):
                raise ValueError(
                    f"Parameter {name} cannot be initialized from "
                    f"loading: shape mismatch, target {tuple(arr.shape)} "
                    f"vs loaded {tuple(src.shape)}")
            arr._set_data(jnp.asarray(src.data if hasattr(src, "data")
                                      else src).astype(arr.data.dtype))
            if self.verbose:
                import logging
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise ValueError(
                    f"Cannot initialize {name}: not found in loaded "
                    "params and no default initializer provided")
            self.default_init(name, arr)


@register
class FusedRNN(Initializer):
    """Initializer for fused-RNN parameters (reference
    initializer.py:719).

    The reference unpacks cuDNN's single packed weight blob and applies
    ``init`` per unfused matrix.  This framework's fused RNN
    (gluon/rnn/rnn_layer.py) keeps per-layer i2h/h2h weights as separate
    parameters (lax.scan consumes them directly — no cuDNN blob), so
    this initializer applies ``init`` to each weight and the LSTM
    forget-gate bias treatment to each bias, which is the same
    post-unpack behavior without the packing round-trip.
    """

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        super().__init__(num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._mode = mode
        self._forget_bias = forget_bias

    def __call__(self, name, arr=None):
        if arr is None:
            name, arr = "", name
        name = getattr(name, "name", name) or ""
        if name.endswith("bias") and self._mode == "lstm":
            import numpy as onp
            b = onp.zeros(arr.shape, "float32")
            n = arr.shape[0] // 4
            b[n:2 * n] = self._forget_bias
            arr._set_data(jnp.asarray(b).astype(arr.data.dtype))
        elif self._init is not None:
            self._init(name, arr)
        else:
            self._init_weight(name, arr)

    def _init_weight(self, name, arr):
        raise ValueError("FusedRNN needs an inner init (or a global "
                         "initializer) for weight parameters")
