"""Custom operator escape hatch (reference python/mxnet/operator.py +
src/operator/custom/custom-inl.h:52-232).

The reference runs Python-callback ops on dedicated worker threads; the
TPU-native design runs them through ``jax.pure_callback`` so a custom op
is legal INSIDE a jitted/compiled graph (host round-trip, documented
slow path) and still differentiable: forward/backward both dispatch to
the user's ``CustomOp`` methods via a ``jax.custom_vjp`` pair.

API surface kept: ``CustomOp`` (forward/backward/assign), ``CustomOpProp``
(list_arguments/list_outputs/infer_shape/infer_type/create_operator),
``register``, and ``nd.Custom(..., op_type=...)``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop", "custom"]

_PROPS: dict = {}


class CustomOp:
    """Base class for user ops (reference operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write src into dst honoring the grad_req (write/add/null)."""
        if req in ("null", 0):
            return
        if req in ("add", "add_to"):
            dst[:] = dst.asnumpy() + (src.asnumpy() if hasattr(src, "asnumpy")
                                      else onp.asarray(src))
        else:
            dst[:] = src


class CustomOpProp:
    """Metadata provider (reference operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type=reg_name``
    (reference operator.py:register / MXCustomOpRegister)."""

    def deco(prop_cls):
        _PROPS[reg_name] = prop_cls
        return prop_cls

    return deco


def get_prop(name):
    try:
        return _PROPS[name]
    except KeyError:
        raise KeyError(f"custom op {name!r} is not registered") from None


class _HostArray:
    """Minimal NDArray-like handed to user CustomOp code: numpy storage
    with the mutation surface (slicing assign, asnumpy) forward/backward
    implementations use."""

    def __init__(self, arr):
        self._a = onp.asarray(arr)

    def asnumpy(self):
        return self._a

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    def __getitem__(self, k):
        return self._a[k]

    def __setitem__(self, k, v):
        self._a[k] = v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v)


def _build_callable(op_type, num_inputs, kwargs):
    """Build the custom_vjp-wrapped jax function for one invocation
    signature. The CustomOp instance is created lazily host-side."""
    prop_cls = get_prop(op_type)
    prop = prop_cls(**kwargs) if kwargs else prop_cls()
    n_out = len(prop.list_outputs())

    def make_op(shapes, dtypes):
        return prop.create_operator(None, shapes, dtypes)

    def _out_dtypes(in_dtypes):
        _, outs, _ = prop.infer_type(list(in_dtypes))
        return [onp.dtype(t) for t in outs]

    def host_forward(*arrays):
        shapes = [a.shape for a in arrays]
        dtypes = [a.dtype for a in arrays]
        _, out_shapes, _ = prop.infer_shape([list(s) for s in shapes])
        out_dtypes = _out_dtypes(dtypes)
        op = make_op(shapes, dtypes)
        in_data = [_HostArray(a) for a in arrays]
        out_data = [_HostArray(onp.zeros(s, t))
                    for s, t in zip(out_shapes, out_dtypes)]
        op.forward(True, ["write"] * n_out, in_data, out_data, [])
        return tuple(o.asnumpy() for o in out_data)

    def host_backward(*arrays):
        # arrays = out_grads + inputs + outputs
        grads = arrays[:n_out]
        ins = arrays[n_out:n_out + num_inputs]
        outs = arrays[n_out + num_inputs:]
        op = make_op([a.shape for a in ins], [a.dtype for a in ins])
        in_grad = [_HostArray(onp.zeros(a.shape, a.dtype)) for a in ins]
        op.backward(["write"] * num_inputs,
                    [_HostArray(g) for g in grads],
                    [_HostArray(a) for a in ins],
                    [_HostArray(a) for a in outs],
                    in_grad, [])
        return tuple(g.asnumpy() for g in in_grad)

    @jax.custom_vjp
    def fn(*inputs):
        shapes = [jnp.shape(x) for x in inputs]
        _, out_shapes, _ = prop.infer_shape([list(s) for s in shapes])
        out_dtypes = _out_dtypes([onp.dtype(str(x.dtype)) for x in inputs])
        result_shape = tuple(
            jax.ShapeDtypeStruct(tuple(s), t)
            for s, t in zip(out_shapes, out_dtypes))
        out = jax.pure_callback(host_forward, result_shape, *inputs,
                                vmap_method="sequential")
        return out[0] if n_out == 1 else out

    def fn_fwd(*inputs):
        out = fn(*inputs)
        outs = (out,) if n_out == 1 else out
        return out, (inputs, outs)

    def fn_bwd(res, g):
        inputs, outs = res
        gs = (g,) if n_out == 1 else g
        result_shape = tuple(
            jax.ShapeDtypeStruct(jnp.shape(x), x.dtype) for x in inputs)
        grads = jax.pure_callback(host_backward, result_shape, *gs, *inputs,
                                  *outs, vmap_method="sequential")
        return tuple(grads)

    fn.defvjp(fn_fwd, fn_bwd)
    return fn, n_out


@functools.lru_cache(maxsize=256)
def _cached_callable(op_type, num_inputs, kwargs_items):
    return _build_callable(op_type, num_inputs, dict(kwargs_items))


def custom(*inputs, op_type, **kwargs):
    """Raw jax-level custom op invocation (used by nd.Custom and the
    symbol frontend)."""
    fn, _ = _cached_callable(op_type, len(inputs),
                             tuple(sorted(kwargs.items())))
    return fn(*inputs)
