"""The ``nd`` namespace: NDArray + every registered op as a function.

Counterpart of reference ``python/mxnet/ndarray/`` (21 kLoC): there the op
functions are code-generated at import from the C++ registry
(register.py:115); here they are generated from the Python-side op
registry — same architecture, one registry feeding every frontend.
"""
from __future__ import annotations

import builtins as _builtins
import functools as _functools
import struct as _struct

import numpy as _onp
import jax as _jax
import jax.numpy as _jnp

from ..base import dtype_from_any as _dtype_from_any
from ..context import Context, current_context
from .ndarray import NDArray, _wrap_outputs, _to_jax
from ..ops import registry as _registry
from ..ops.registry import invoke as _invoke
from ..ops import control_flow as _cf

# ---------------------------------------------------------------------------
# generated op wrappers (reference python/mxnet/ndarray/register.py:115)
# ---------------------------------------------------------------------------

def _make_wrapper(op_name):
    op = _registry.get_op(op_name)

    def fn(*args, out=None, **kwargs):
        return _invoke(op, *args, out=out, **kwargs)

    fn.__name__ = op_name
    fn.__qualname__ = op_name
    fn.__doc__ = (op.fn.__doc__ or f"Operator {op_name} (auto-generated wrapper).")
    return fn


_g = globals()
for _name in _registry.list_ops():
    if _name not in _g:
        _g[_name] = _make_wrapper(_name)

# Per-element-parameter samplers: the reference exposes these as
# `mx.nd.sample_normal(mu, sigma, shape=n)` with no explicit RNG state
# (src/operator/random/sample_op.cc); here the wrapper draws the key
# from the global stream so the registry op itself stays pure.
def _make_sample_wrapper(op_name):
    op = _registry.get_op(op_name)

    def fn(*params, shape=(), dtype=None, out=None, **kw):
        from .. import random as _rng
        if dtype is not None:
            kw["dtype"] = dtype
        # key goes by keyword: distribution params may legally arrive as
        # keywords too (reference API), and a positional key would then
        # collide with the first parameter slot
        return _invoke(op, *params, out=out, shape=shape,
                       key=_rng.next_key(), **kw)

    fn.__name__ = op_name
    fn.__doc__ = op.fn.__doc__
    return fn


for _name in ("sample_uniform", "sample_normal", "sample_gamma",
              "sample_exponential", "sample_poisson",
              "sample_negative_binomial",
              "sample_generalized_negative_binomial"):
    # the reference-internal alias (`_sample_*`) must key-inject too
    _g[_name] = _g["_" + _name] = _make_sample_wrapper(_name)

# pythonic aliases matching the reference nd namespace
_dense_dot = _g["dot"]


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None):
    """Dot product with sparse storage dispatch (reference
    src/operator/tensor/dot-inl.h FComputeEx: csr/row_sparse lhs hit the
    sparse kernels in ops/sparse_ops.py instead of densifying).

    The sparse branches go through ``invoke`` so autograd records the
    op — gradients flow to the dense rhs exactly like the dense path
    (the sparse lhs pattern is constant, matching reference semantics
    where the csr structure is not differentiable).
    """
    from .sparse import CSRNDArray, RowSparseNDArray
    if isinstance(lhs, CSRNDArray) and not transpose_b:
        n_out = lhs._dense_shape[1] if transpose_a else lhs._dense_shape[0]
        return _invoke("_sparse_csr_dot_dense",
                       lhs._csr_data, lhs._csr_indices, lhs._csr_indptr,
                       rhs, transpose_lhs=bool(transpose_a),
                       n_rows=int(n_out), out=out)
    if isinstance(lhs, RowSparseNDArray) and not (transpose_a or transpose_b):
        return _invoke("_sparse_row_sparse_dot_dense",
                       lhs._rs_values, lhs._rs_indices, rhs,
                       n_rows=int(lhs._dense_shape[0]), out=out)
    return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b, out=out)
from .sparse import cast_storage  # noqa: E402  (reference nd.cast_storage)
concatenate = _g["concat"]
elemwise_add = _g["add"]
waitall = None  # set below


def Dropout(data, key=None, p=0.5, mode=None, axes=(), out=None, **_ignored):
    """MXNet-parity dropout: applies only under autograd train mode
    (reference src/operator/nn/dropout-inl.h mode semantics); the PRNG
    key is drawn from the global stream when not given."""
    from .. import autograd
    from .. import random as _random
    if mode is None:
        mode = "training" if autograd.is_training() else "inference"
    # reference src/operator/nn/dropout-inl.h:348: drop when
    # (is_train || mode == kAlways)
    if mode not in ("training", "always") or p <= 0.0:
        return identity(data, out=out)
    if key is None:
        key = _random.next_key()
    return _invoke("Dropout", data, key, p=p, mode="training", axes=axes,
                   out=out)


dropout = Dropout


class _Contrib:
    """``nd.contrib`` namespace (foreach/while_loop/cond + extras)."""

    foreach = staticmethod(_cf.foreach)
    while_loop = staticmethod(_cf.while_loop)
    cond = staticmethod(_cf.cond)

    @staticmethod
    def boolean_mask(data, index, axis=0):
        """Dynamic-shape boolean mask — eager only (host round-trip).

        Reference src/operator/contrib/boolean_mask.cc.  XLA cannot
        express dynamic output shapes; the concrete-value path is the
        documented TPU fallback.
        """
        mask = _onp.asarray(index.asnumpy()).astype(bool)
        return NDArray(data.data[_onp.nonzero(mask)[0]] if axis == 0
                       else _jnp.compress(mask, data.data, axis=axis),
                       ctx=data.ctx)

    @staticmethod
    def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
        # delegate to the registered op so eager/symbolic/contrib paths
        # share one behavior (ops/image_ops.py arange_like)
        return _invoke("arange_like", data, start=start, step=step,
                       repeat=repeat, axis=axis)


contrib = _Contrib()

# detection op family (reference mx.nd.contrib.MultiBox*/box_* surface;
# ops defined in ops/contrib_ops.py, wrappers generated above)
for _cname, _gname in (
        ("MultiBoxPrior", "_contrib_MultiBoxPrior"),
        ("MultiBoxTarget", "_contrib_MultiBoxTarget"),
        ("MultiBoxDetection", "_contrib_MultiBoxDetection"),
        ("box_nms", "_contrib_box_nms"),
        ("box_iou", "_contrib_box_iou"),
        ("bipartite_matching", "_contrib_bipartite_matching"),
        ("ROIAlign", "_contrib_ROIAlign")):
    setattr(_Contrib, _cname, staticmethod(_g[_gname]))


class _LinalgNS:
    def __getattr__(self, name):
        return _g["linalg_" + name]


linalg = _LinalgNS()

# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference nd.array)."""
    return NDArray(source_array, ctx=ctx or current_context(), dtype=dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_jnp.zeros(shape, _dtype_from_any(dtype)), ctx=ctx or current_context())


def ones(shape, ctx=None, dtype="float32", **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_jnp.ones(shape, _dtype_from_any(dtype)), ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_jnp.full(shape, val, _dtype_from_any(dtype)), ctx=ctx or current_context())


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros_like(a, **kw):
    return NDArray(_jnp.zeros_like(a.data), ctx=a.ctx)


def ones_like(a, **kw):
    return NDArray(_jnp.ones_like(a.data), ctx=a.ctx)


def full_like(a, fill_value, **kw):
    return NDArray(_jnp.full_like(a.data, fill_value), ctx=a.ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = _jnp.arange(start, stop, step, dtype=_dtype_from_any(dtype))
    if repeat > 1:
        out = _jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return NDArray(_jnp.linspace(start, stop, num, endpoint=endpoint,
                                 dtype=_dtype_from_any(dtype)),
                   ctx=ctx or current_context())


def eye(N, M=None, k=0, ctx=None, dtype="float32"):
    return NDArray(_jnp.eye(N, M, k, dtype=_dtype_from_any(dtype)),
                   ctx=ctx or current_context())


def meshgrid(*arrays, indexing="xy"):
    outs = _jnp.meshgrid(*[a.data for a in arrays], indexing=indexing)
    return [NDArray(o, ctx=arrays[0].ctx) for o in outs]


def from_dlpack(capsule):
    return NDArray(_jnp.asarray(_jax.dlpack.from_dlpack(capsule)))


def to_dlpack_for_read(arr):
    return arr.data.__dlpack__()


to_dlpack_for_write = to_dlpack_for_read


def waitall():
    """Block until all async work completes and surface errors
    (reference MXNDArrayWaitAll)."""
    from .. import engine
    engine.get_engine().wait_for_all()
    (_jax.effects_barrier if hasattr(_jax, "effects_barrier") else lambda: None)()


def add_n(*args, out=None):
    acc = args[0].data
    for a in args[1:]:
        acc = acc + a.data
    return _wrap_outputs(acc, args, out=out)


ElementWiseSum = add_n


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Utility mirrored from gluon.utils: slice batch across contexts."""
    n = len(ctx_list)
    if not isinstance(data, NDArray):
        data = array(data)
    size = data.shape[batch_axis]
    step = size // n
    slices = []
    for i, ctx in enumerate(ctx_list):
        begin = i * step
        end = (i + 1) * step if i < n - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)].as_in_context(ctx))
    return slices


# ---------------------------------------------------------------------------
# serialization — reference-compatible TLV wire format
# (src/ndarray/ndarray.cc:1679-1924; codec in params_io.py).  Files
# written here load in the reference runtime and vice versa, satisfying
# the SURVEY.md §5.4 backwards-compat axis.  The round-1 private
# "MXTPU001" container is still readable for old checkpoints.
# ---------------------------------------------------------------------------

_MAGIC = b"MXTPU001"


def save(fname, data):
    """Save a list or dict of NDArrays in the reference ``.params``
    format (reference nd.save, ndarray.cc:1926 kMXAPINDArrayListMagic) —
    files written here load in the reference runtime."""
    from . import params_io
    from .sparse import RowSparseNDArray, CSRNDArray
    if isinstance(data, NDArray):
        data = [data]
    named = isinstance(data, dict)
    items = list(data.items()) if named else [("", v) for v in data]
    wire = []
    for key, arr in items:
        if isinstance(arr, RowSparseNDArray):
            vals = _onp.asarray(arr._rs_values)
            idx = _onp.asarray(arr._rs_indices, _onp.int64)
            wire.append((key, (vals, arr._dense_shape, 1, [idx])))
        elif isinstance(arr, CSRNDArray):
            vals = _onp.asarray(arr._csr_data)
            indptr = _onp.asarray(arr._csr_indptr, _onp.int64)
            idx = _onp.asarray(arr._csr_indices, _onp.int64)
            wire.append((key, (vals, arr._dense_shape, 2, [indptr, idx])))
        else:
            v = arr.data if isinstance(arr, NDArray) else _jnp.asarray(arr)
            wire.append((key, _onp.asarray(v)))
    from ..filesystem import open_uri
    with open_uri(fname, "wb") as f:
        f.write(params_io.save_bytes(wire, named=named))


def load(fname):
    """Load arrays saved by the reference runtime or by :func:`save`
    (reference nd.load); also reads the round-1 MXTPU001 container."""
    from . import params_io
    from .sparse import RowSparseNDArray, CSRNDArray
    from ..filesystem import open_uri
    with open_uri(fname, "rb") as f:
        raw = f.read()
    if raw[:8] != _MAGIC:
        arrays, names = params_io.load_bytes(raw)
        wrapped = []
        for values, stype, aux, shape in arrays:
            if values is None:
                wrapped.append(None)
            elif stype == 1:
                wrapped.append(RowSparseNDArray(
                    _jnp.asarray(values), _onp.asarray(aux[0]), shape))
            elif stype == 2:
                wrapped.append(CSRNDArray(
                    _jnp.asarray(values), _onp.asarray(aux[1]),
                    _onp.asarray(aux[0]), shape))
            else:
                wrapped.append(NDArray(_jnp.asarray(values)))
        if names:
            return dict(zip(names, wrapped))
        return wrapped
    # ---- legacy MXTPU001 container -------------------------------------
    with open(fname, "rb") as f:
        magic = f.read(8)
        n = _struct.unpack("<q", f.read(8))[0]
        out = {}
        keyed = True
        arrays = []
        for _ in range(n):
            klen = _struct.unpack("<q", f.read(8))[0]
            key = f.read(klen).decode()
            dlen = _struct.unpack("<q", f.read(8))[0]
            dtype_name = f.read(dlen).decode()
            ndim = _struct.unpack("<q", f.read(8))[0]
            shape = tuple(_struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
            nbytes = _struct.unpack("<q", f.read(8))[0]
            buf = f.read(nbytes)
            if dtype_name == "bfloat16":
                np_val = _onp.frombuffer(buf, dtype="float32").reshape(shape)
                arr = NDArray(_jnp.asarray(np_val).astype(_jnp.bfloat16))
            else:
                np_val = _onp.frombuffer(buf, dtype=dtype_name).reshape(shape)
                arr = NDArray(np_val)
            if not key:
                keyed = False
            arrays.append((key, arr))
        if keyed and _builtins.any(k for k, _ in arrays):
            return {k: v for k, v in arrays}
        return [v for _, v in arrays]


def save_parameters(fname, params):
    save(fname, params)


def load_parameters(fname):
    return load(fname)


def imdecode(buf, flag=1, to_rgb=True):
    from ..image import imdecode as _imdecode
    return _imdecode(buf, flag=flag, to_rgb=to_rgb)


from . import random  # noqa: E402  (needs creation ops above)
from . import sparse  # noqa: E402
from .random import uniform as random_uniform_eager  # noqa: F401
