"""NDArray: the imperative data plane over PJRT buffers.

TPU-native re-design of the reference NDArray (include/mxnet/ndarray.h:82,
src/ndarray/) — SURVEY.md §7 stage 1.  The reference NDArray is a shared
``Chunk`` (ndarray.h:820-1091) holding mutable device memory plus an engine
variable; views (slice/reshape) alias the chunk, and in-place ops mutate it.

XLA buffers are immutable, so mutation is re-designed functionally:

* ``_Chunk`` holds the current ``jax.Array`` *value* plus an engine ``Var``
  whose version bumps on every write — in-place ops (``x += y``,
  ``x[1:3] = v``) compute a new value with ``Array.at[...]`` (which XLA
  turns into an in-place donation when safe) and swap it into the chunk.
* Views created by basic slicing / ``reshape`` share the chunk and record
  an index / shape transform: reads re-slice the current chunk value
  (lazy, fused by XLA), writes scatter back into the chunk — so mutation
  through a view is visible through the base and vice versa, matching
  reference view semantics.
* ``wait_to_read`` / ``asnumpy`` block on the underlying buffer, and
  surface async device errors there, mirroring the engine's exception
  propagation contract (reference threaded_engine.cc:422-522).

The array may also wrap a JAX tracer — the same class flows through
``hybridize`` tracing, which is how whole blocks compile to one XLA
program (the CachedOp analog).
"""
from __future__ import annotations

import weakref

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import dtype_from_any, integer_types, numeric_types
from ..context import Context, current_context
from .. import engine as _engine_mod
from .. import profiler as _profiler
from ..analysis import race as _race
from ..ops import bulking as _bulking

__all__ = ["NDArray", "_wrap_outputs", "_to_jax"]


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _to_jax(value, ctx: Context | None = None, dtype=None):
    """Convert arbitrary input to a jax.Array placed on ctx's device."""
    dtype = dtype_from_any(dtype)
    if isinstance(value, NDArray):
        value = value.data
    if _is_tracer(value):
        return value.astype(dtype) if dtype is not None else value
    if isinstance(value, jax.Array):
        arr = value if dtype is None else value.astype(dtype)
    else:
        if dtype is None and not isinstance(value, onp.ndarray):
            # python lists/scalars default to float32 (reference
            # nd.array semantics: dtype defaults to float32 unless the
            # source carries a dtype)
            dtype = jnp.dtype(jnp.float32)
        np_val = onp.asarray(value, dtype=None if dtype is None else onp.dtype(dtype.name) if dtype.name != "bfloat16" else None)
        if dtype is not None and dtype.name == "bfloat16":
            arr = jnp.array(np_val).astype(jnp.bfloat16)
        else:
            if np_val.dtype == onp.float64 and dtype is None:
                np_val = np_val.astype(onp.float32)  # default_dtype like reference
            # jnp.array (copy) — NOT asarray: the CPU backend may zero-copy
            # alias numpy buffers, and chunks must own their storage
            arr = jnp.array(np_val)
        if dtype is not None:
            arr = arr.astype(dtype)
    if ctx is not None and not _is_tracer(arr):
        dev = ctx.jax_device
        if not (arr.committed and next(iter(arr.devices())) == dev) :
            arr = jax.device_put(arr, dev)
    return arr


class _Chunk:
    """Shared storage cell: current value + engine var (version counter)."""

    # weakref'd by PendingArray holder tracking: at segment flush a
    # placeholder no surviving chunk holds is a dead temporary whose
    # buffer never leaves the compiled program (ops/bulking.py)
    __slots__ = ("array", "var", "ctx", "__weakref__")

    def __init__(self, array, ctx):
        self.array = array
        self.ctx = ctx
        if type(array) is _bulking.PendingArray:
            array._holders.append(weakref.ref(self))
        self.var = _engine_mod.get_engine().new_variable("ndarray")
        if _race.enabled:
            # arrays born inside an engine closure are op-local: exempt
            # from that op's declared read/write sets (analysis/race.py)
            _race.note_create(self.var)
        if _profiler._alloc_tracking and not _is_tracer(array):
            # storage-profiler hook (reference storage_profiler.cc):
            # tag this chunk's bytes with the active profiler scope
            try:
                _profiler.record_alloc(
                    array.size * array.dtype.itemsize, array.shape,
                    array.dtype, ctx)
            except Exception:  # mxlint: allow-broad-except(best-effort profiler attribution must never fail an allocation)
                pass

    def write(self, new_array):
        if type(new_array) is _bulking.PendingArray:
            # defensive: every chunk holding a placeholder must be in
            # its holder set or the flush would drop a live output
            new_array._holders.append(weakref.ref(self))
        self.array = new_array
        self.var._version += 1
        if _race.enabled:
            _race.note_write(self.var)


class NDArray:
    """A multi-dimensional array on a device context.

    Mirrors the user-facing surface of the reference NDArray
    (python/mxnet/ndarray/ndarray.py): numpy conversion, arithmetic with
    broadcasting, slicing with view semantics, in-place mutation,
    ``attach_grad``/``backward`` autograd hooks, context movement.
    """

    __slots__ = ("_chunk", "_index", "_vshape", "_grad", "_grad_req",
                 "_tape_node", "__weakref__")

    def __init__(self, data, ctx: Context | None = None, dtype=None,
                 _chunk: _Chunk | None = None, _index=None, _vshape=None):
        if _chunk is not None:
            self._chunk = _chunk
        else:
            if ctx is None:
                ctx = current_context() if not isinstance(data, NDArray) else data.ctx
            self._chunk = _Chunk(_to_jax(data, ctx, dtype), ctx)
        self._index = _index
        self._vshape = _vshape
        self._grad = None
        self._grad_req = None
        self._tape_node = None

    # ------------------------------------------------------------------
    # storage access
    # ------------------------------------------------------------------
    @property
    def data(self):
        """Current value as a jax.Array (views re-slice lazily).

        This is a bulking sync point: a chunk holding a PendingArray
        (deferred segment output, ops/bulking.py) flushes its segment
        here and the concrete value is swapped in — no version bump,
        materialization is not a write."""
        if _race.enabled:
            _race.note_read(self._chunk.var)
        a = self._chunk.array
        if type(a) is _bulking.PendingArray:
            v = _bulking.resolve(a)
            if self._chunk.array is a:
                self._chunk.array = v
            a = v
        if self._index is not None:
            a = a[self._index]
        if self._vshape is not None:
            a = a.reshape(self._vshape)
        return a

    def _set_data(self, new):
        """Functional write-back honouring view aliasing."""
        if isinstance(new, onp.ndarray):
            # force a device copy: the CPU backend may zero-copy alias the
            # numpy buffer, which the caller is free to mutate/free
            new = jnp.array(new)
        if self._index is None and self._vshape is None:
            self._chunk.write(new)
        elif self._index is not None:
            if type(self._chunk.array) is _bulking.PendingArray:
                self.data  # sync point: materialize before scatter-back
            base = self._chunk.array
            target_shape = base[self._index].shape
            self._chunk.write(base.at[self._index].set(
                jnp.broadcast_to(jnp.asarray(new, base.dtype), target_shape)))
        else:  # pure reshape view
            self._chunk.write(jnp.reshape(jnp.asarray(new),
                                          self._chunk.array.shape))

    @property
    def _is_view(self):
        return self._index is not None or self._vshape is not None

    def _in_graph(self):
        return (self._grad_req not in (None, "null")) or self._tape_node is not None

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        # pending (bulked) values carry their abstract shape: metadata
        # inspection must not force a segment flush
        a = self._chunk.array
        if type(a) is _bulking.PendingArray and self._index is None:
            return tuple(self._vshape) if self._vshape is not None \
                else tuple(a.shape)
        return tuple(self.data.shape)

    @property
    def dtype(self):
        a = self._chunk.array
        dt = a.dtype if type(a) is _bulking.PendingArray else self.data.dtype
        return onp.dtype(dt.name) if dt.name != "bfloat16" else dt

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(onp.prod(self.shape)) if self.shape else 1

    @property
    def ctx(self) -> Context:
        return self._chunk.ctx

    context = ctx

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def handle(self):  # reference parity: opaque handle
        return self._chunk

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        if _is_tracer(self._chunk.array):
            return f"NDArray(traced, shape={self.shape}) @{self.ctx}"
        return f"\n{self.asnumpy()}\n<NDArray {self.shape} @{self.ctx}>"

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asnumpy().item())

    def __float__(self):
        return float(self.asnumpy().item())

    def __int__(self):
        return int(self.asnumpy().item())

    def __index__(self):
        return int(self)

    # ------------------------------------------------------------------
    # host transfer / sync
    # ------------------------------------------------------------------
    def asnumpy(self) -> onp.ndarray:
        """Blocking copy to host (reference ndarray.py asnumpy).

        This is the async-error surface: exceptions raised by device
        execution propagate here.
        """
        a = self.data
        if _is_tracer(a):
            raise RuntimeError("cannot asnumpy() a traced NDArray inside hybridize")
        if a.dtype == jnp.bfloat16:
            return onp.asarray(a.astype(jnp.float32))
        return onp.asarray(a)

    def __array__(self, dtype=None, copy=None):
        """numpy conversion protocol: without this, np.asarray(ndarray)
        falls back to the SEQUENCE protocol and crawls __getitem__
        row-by-row — O(n) device round trips that look like a hang.

        A host copy is always materialized from the device buffer, so
        copy=False cannot be honored (numpy 2 protocol: raise)."""
        if copy is False:
            raise ValueError(
                "NDArray->numpy always copies (device buffer); "
                "np.asarray(..., copy=False) cannot be satisfied")
        out = self.asnumpy()
        return out.astype(dtype) if dtype is not None else out

    def __array_function__(self, func, types, args, kwargs):
        """NumPy dispatch protocol (reference mx.np
        numpy_dispatch_protocol.py / test_numpy_interoperability.py):
        ``onp.mean(nd_array)`` etc. route to the framework's numpy
        namespace — staying on device and returning NDArray — with a
        host-numpy fallback for functions the namespace lacks."""
        from .. import numpy as mxnp
        f = getattr(mxnp, func.__name__, None)
        if callable(f):
            try:
                return f(*args, **kwargs)
            except TypeError:
                pass  # signature mismatch (out=, where=...): host path

        def host(v):
            # DEEP conversion — an NDArray left inside a nested sequence
            # or kwarg re-dispatches right back here (RecursionError)
            if isinstance(v, NDArray):
                return v.asnumpy()
            if isinstance(v, (list, tuple)):
                return type(v)(host(e) for e in v)
            if isinstance(v, dict):
                return {k: host(e) for k, e in v.items()}
            return v
        return func(*host(args), **host(kwargs))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def wait_to_read(self):
        a = self.data
        if not _is_tracer(a):
            jax.block_until_ready(a)
        _engine_mod.get_engine().throw_pending(self._chunk.var)

    def wait_to_write(self):
        self.wait_to_read()

    # ------------------------------------------------------------------
    # copies / context movement
    # ------------------------------------------------------------------
    def copy(self) -> "NDArray":
        return NDArray(self.data + 0, ctx=self.ctx)

    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(jax.device_put(self.data, other.jax_device), ctx=other)
        other._set_data(_to_jax(self.data, other.ctx, other.data.dtype))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def astype(self, dtype, copy=True):
        dt = dtype_from_any(dtype)
        if not copy and jnp.dtype(self.data.dtype) == dt:
            return self
        return NDArray(self.data.astype(dt), ctx=self.ctx)

    def detach(self) -> "NDArray":
        out = NDArray(self.data, ctx=self.ctx)
        return out

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference ndarray.py attach_grad)."""
        self._grad = NDArray(jnp.zeros(self.shape, self.data.dtype), ctx=self.ctx)
        self._grad_req = grad_req

    @property
    def grad(self):
        return self._grad

    def zero_grad(self):
        if self._grad is not None:
            self._grad._set_data(jnp.zeros(self._grad.shape, self._grad.data.dtype))

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    @staticmethod
    def _is_basic_index(key):
        if isinstance(key, (slice, *integer_types)) or key is None or key is Ellipsis:
            return True
        if isinstance(key, tuple):
            return all(isinstance(k, (slice, *integer_types)) or k is None or k is Ellipsis
                       for k in key)
        return False

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key.data
        if self._is_basic_index(key) and not self._is_view and not _is_tracer(self._chunk.array):
            # view sharing the chunk (reference: slice returns a view of
            # the same Chunk — ndarray.h views share shandle)
            return NDArray(None, _chunk=self._chunk, _index=key)
        return NDArray(self.data[key], ctx=self.ctx)

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            key = key.data
        if isinstance(value, NDArray):
            value = value.data
        if type(self._chunk.array) is _bulking.PendingArray:
            self.data  # sync point: materialize before the in-place write
        base = self._chunk.array
        if self._is_view:
            # write through the composed view
            data = self.data.at[key].set(jnp.asarray(value, base.dtype)
                                         if not isinstance(value, (int, float)) else value)
            self._set_data(data)
            return
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, base.dtype), base.shape)
            self._chunk.write(jnp.asarray(new))
            return
        self._chunk.write(base.at[key].set(
            value if isinstance(value, (int, float)) else jnp.asarray(value, base.dtype)))

    def slice(self, begin, end, step=None):
        idx = tuple(slice(b, e, s) for b, e, s in
                    zip(begin, end, step or [None] * len(begin)))
        return self[idx]

    def take(self, indices, axis=0, mode="clip"):
        from ..ops.registry import invoke
        return invoke("take", self, indices, axis=axis, mode=mode)

    # ------------------------------------------------------------------
    # shape manipulation (view-producing where the reference's are views)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        # reference Reshape special codes (matrix_op-inl.h:95): 0 copy,
        # -1 infer, -2 copy-rest, -3 merge, -4 split, reverse=right-to-left
        from ..ops.shape_ops import infer_reshape
        shape = infer_reshape(self.shape, shape,
                              reverse=bool(kwargs.get("reverse", False)))
        if self._grad_live():
            return self._op("reshape", shape=shape)
        if not self._is_view and not _is_tracer(self._chunk.array):
            return NDArray(None, _chunk=self._chunk, _vshape=shape)
        return NDArray(self.data.reshape(shape), ctx=self.ctx)

    def _grad_live(self):
        """True when this array is on the live autograd tape — view/shape
        methods must then route through the op registry so the recorded
        graph stays connected (Imperative::RecordOp analog)."""
        from .. import autograd
        return autograd.is_recording() and self._in_graph()

    def reshape_like(self, other):
        return self.reshape(other.shape)

    # shape/view methods route through the op registry unconditionally so
    # recording and eager paths share ONE implementation (invoke() already
    # takes the fast jitted path when no gradient is live); only reshape
    # above keeps its chunk-sharing view special case for in-place ops
    def expand_dims(self, axis):
        return self._op("expand_dims", axis=axis)

    def squeeze(self, axis=None):
        return self._op("squeeze", axis=axis)

    def flatten(self):
        return self.reshape((self.shape[0], -1)) if self.ndim > 1 else self.reshape((-1,))

    def transpose(self, axes=None):
        return self._op("transpose", axes=tuple(axes) if axes else None)

    def swapaxes(self, a, b):
        return self._op("swapaxes", dim1=a, dim2=b)

    def broadcast_to(self, shape):
        return self._op("broadcast_to", shape=tuple(shape))

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return self._op("tile", reps=tuple(reps)
                        if isinstance(reps, (tuple, list)) else reps)

    def repeat(self, repeats, axis=None):
        return self._op("repeat", repeats=repeats, axis=axis)

    def pad(self, pad_width, mode="constant", constant_value=0):
        if isinstance(pad_width, (tuple, list)):
            pad_width = tuple(tuple(p) if isinstance(p, (tuple, list)) else p
                              for p in pad_width)
        return self._op("pad", pad_width=pad_width, mode=mode,
                        constant_value=constant_value)

    def diag(self, k=0):
        return self._op("diag", k=k)

    def tostype(self, stype):
        if stype != "default":
            from . import sparse
            return sparse.cast_storage(self, stype)
        return self

    def as_np_ndarray(self):
        from .. import numpy as mxnp
        return mxnp.ndarray(self.data, ctx=self.ctx)

    # ------------------------------------------------------------------
    # arithmetic (delegates to the op registry for autograd integration)
    # ------------------------------------------------------------------
    def _binop(self, name, other, reverse=False):
        from ..ops.registry import invoke

        if isinstance(other, NDArray) or isinstance(other, numeric_types):
            a, b = (other, self) if reverse else (self, other)
            return invoke(name, a, b)
        return NotImplemented

    def __add__(self, o): return self._binop("add", o)
    def __radd__(self, o): return self._binop("add", o, True)
    def __sub__(self, o): return self._binop("subtract", o)
    def __rsub__(self, o): return self._binop("subtract", o, True)
    def __mul__(self, o): return self._binop("multiply", o)
    def __rmul__(self, o): return self._binop("multiply", o, True)
    def __truediv__(self, o): return self._binop("divide", o)
    def __rtruediv__(self, o): return self._binop("divide", o, True)
    def __floordiv__(self, o): return self._binop("floor_divide", o)
    def __rfloordiv__(self, o): return self._binop("floor_divide", o, True)
    def __mod__(self, o): return self._binop("mod", o)
    def __rmod__(self, o): return self._binop("mod", o, True)
    def __pow__(self, o): return self._binop("power", o)
    def __rpow__(self, o): return self._binop("power", o, True)
    def __matmul__(self, o): return self._binop("matmul", o)

    def __neg__(self):
        from ..ops.registry import invoke
        return invoke("negative", self)

    def __abs__(self):
        from ..ops.registry import invoke
        return invoke("abs", self)

    def __eq__(self, o): return self._cmp("equal", o)
    def __ne__(self, o): return self._cmp("not_equal", o)
    def __lt__(self, o): return self._cmp("lesser", o)
    def __le__(self, o): return self._cmp("lesser_equal", o)
    def __gt__(self, o): return self._cmp("greater", o)
    def __ge__(self, o): return self._cmp("greater_equal", o)

    def _cmp(self, name, other):
        from ..ops.registry import invoke
        if isinstance(other, (NDArray, *numeric_types)):
            return invoke(name, self, other)
        return NotImplemented

    __hash__ = object.__hash__

    # in-place: mutate the chunk (not recorded — reference raises on
    # in-place mutation of arrays needing grad inside record scope too)
    def _inplace(self, name, other):
        from .. import autograd
        if autograd.is_recording() and self._in_graph():
            raise RuntimeError(
                "in-place operations on arrays in the autograd graph are "
                "not supported inside record()")
        o = other.data if isinstance(other, NDArray) else other
        fn = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
              "div": jnp.divide}[name]
        self._set_data(fn(self.data, o).astype(self.data.dtype))
        return self

    def __iadd__(self, o): return self._inplace("add", o)
    def __isub__(self, o): return self._inplace("sub", o)
    def __imul__(self, o): return self._inplace("mul", o)
    def __itruediv__(self, o): return self._inplace("div", o)

    # ------------------------------------------------------------------
    # reductions & common math as methods
    # ------------------------------------------------------------------
    def _op(self, name, **kw):
        from ..ops.registry import invoke
        return invoke(name, self, **kw)

    def sum(self, axis=None, keepdims=False):
        return self._op("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._op("mean", axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._op("prod", axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._op("max", axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._op("min", axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._op("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._op("argmin", axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._op("norm", ord=ord, axis=axis, keepdims=keepdims)

    def clip(self, a_min=None, a_max=None):
        return self._op("clip", a_min=a_min, a_max=a_max)

    def abs(self):
        return self._op("abs")

    def sqrt(self):
        return self._op("sqrt")

    def square(self):
        return self._op("square")

    def exp(self):
        return self._op("exp")

    def log(self):
        return self._op("log")

    def sigmoid(self):
        return self._op("sigmoid")

    def tanh(self):
        return self._op("tanh")

    def relu(self):
        return self._op("relu")

    def softmax(self, axis=-1):
        return self._op("softmax", axis=axis)

    def log_softmax(self, axis=-1):
        return self._op("log_softmax", axis=axis)

    def dot(self, other):
        from ..ops.registry import invoke
        return invoke("dot", self, other)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return self._op("one_hot", depth=depth, on_value=on_value,
                        off_value=off_value)

    def topk(self, k=1, axis=-1, ret_typ="indices", is_ascend=False):
        return self._op("topk", k=k, axis=axis, ret_typ=ret_typ,
                        is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return self._op("sort", axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        return self._op("argsort", axis=axis, is_ascend=is_ascend)


def _wrap_outputs(out_data, inputs, out=None):
    """Wrap raw jax outputs into NDArrays on the inferred context."""
    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            ctx = x.ctx
            break
    if ctx is None:
        ctx = current_context()

    def wrap_one(a, target):
        if target is not None:
            target._set_data(a)
            return target
        nd = NDArray.__new__(NDArray)
        nd._chunk = _Chunk(a, ctx)
        nd._index = None
        nd._vshape = None
        nd._grad = None
        nd._grad_req = None
        nd._tape_node = None
        return nd

    if isinstance(out_data, (tuple, list)):
        outs = out if isinstance(out, (tuple, list)) else [None] * len(out_data)
        return tuple(wrap_one(a, t) for a, t in zip(out_data, outs))
    return wrap_one(out_data, out)
