"""Reference-compatible ``.params`` serialization (wire format of
``NDArray::Save/Load``, reference src/ndarray/ndarray.cc:1679-1924).

Layout (all little-endian):

  file      := uint64 0x112 (kMXAPINDArrayListMagic) | uint64 reserved=0
             | uint64 n_arrays | ndarray*  | uint64 n_keys
             | (uint64 len | utf8 bytes)*                 [dmlc::Stream]
  ndarray   := uint32 magic | payload
    magic 0xF993fac9 (V2) / 0xF993faca (V3, np-shape):
      int32 stype | [sparse: tshape storage_shape] | tshape shape
      | int32 dev_type | int32 dev_id | int32 type_flag
      | [sparse: (int32 aux_type | tshape aux_shape) * nad]
      | raw data | [sparse: raw aux data * nad]
    magic 0xF993fac8 (V1): tshape shape | ctx | int32 type_flag | raw
    other magic = ndim (legacy): uint32 dims[ndim] | ctx | int32 type_flag | raw
  tshape    := int32 ndim | int64 dims[ndim]              [mxnet tuple.h:731]
  ctx       := int32 dev_type | int32 dev_id              [mxnet base.h:145]

Storage types (ndarray.h:61): 0 dense, 1 row_sparse (1 aux: indices),
2 csr (2 aux: indptr, indices).  Type flags (mshadow base.h:329): 0 f32,
1 f64, 2 f16, 3 u8, 4 i32, 5 i8, 6 i64, 7 bool, 12 bf16.

Writing emits V2 dense/row_sparse/csr records, so checkpoints produced
here load in the reference runtime and vice versa — the
backwards-compatibility axis of SURVEY.md §5.4 (the reference's own
model_backwards_compatibility_check relies on this format being stable).
"""
from __future__ import annotations

import struct

import numpy as onp
import ml_dtypes

LIST_MAGIC = 0x112
V1_MAGIC = 0xF993FAC8
V2_MAGIC = 0xF993FAC9
V3_MAGIC = 0xF993FACA

# mshadow type_flag <-> numpy dtype
_FLAG2DT = {
    0: onp.dtype("float32"), 1: onp.dtype("float64"),
    2: onp.dtype("float16"), 3: onp.dtype("uint8"),
    4: onp.dtype("int32"), 5: onp.dtype("int8"), 6: onp.dtype("int64"),
    7: onp.dtype(bool), 12: onp.dtype(ml_dtypes.bfloat16),
}
_DT2FLAG = {v: k for k, v in _FLAG2DT.items()}

_STYPE_NAUX = {0: 0, 1: 1, 2: 2}  # dense, row_sparse, csr


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, n):
        if self.pos + n > len(self.buf):
            raise ValueError("truncated .params stream")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]

    def tshape(self):
        ndim = self.i32()
        if ndim < 0:  # unknown shape (np semantics)
            return None
        return tuple(struct.unpack(f"<{ndim}q", self.read(8 * ndim)))


def _read_ndarray(r: _Reader):
    """One NDArray record → (values, stype, aux_list, logical_shape).

    For dense records values.shape == logical_shape; for sparse records
    values holds the storage buffer and logical_shape the dense shape.
    """
    magic = r.u32()
    if magic in (V2_MAGIC, V3_MAGIC):
        stype = r.i32()
        nad = _STYPE_NAUX.get(stype)
        if nad is None:
            raise ValueError(f"unknown storage type {stype}")
        sshape = r.tshape() if nad else None
        shape = r.tshape()
        if shape is None or len(shape) == 0:
            return None, 0, [], ()
        r.i32(), r.i32()  # context: dev_type, dev_id (placement ignored)
        flag = r.i32()
        aux_meta = [(r.i32(), r.tshape()) for _ in range(nad)]
        dshape = sshape if nad else shape
        dt = _FLAG2DT[flag]
        n = int(onp.prod(dshape)) if dshape else 1
        data = onp.frombuffer(r.read(n * dt.itemsize), dt).reshape(dshape)
        aux = []
        for aflag, ashape in aux_meta:
            adt = _FLAG2DT[aflag]
            an = int(onp.prod(ashape)) if ashape else 1
            aux.append(onp.frombuffer(r.read(an * adt.itemsize),
                                      adt).reshape(ashape))
        return data, stype, aux, shape
    if magic == V1_MAGIC:
        shape = r.tshape()
    else:  # oldest format: magic IS ndim, uint32 dims
        ndim = magic
        shape = tuple(struct.unpack(f"<{ndim}I", r.read(4 * ndim)))
    if not shape:
        return None, 0, [], ()
    r.i32(), r.i32()  # context
    flag = r.i32()
    dt = _FLAG2DT[flag]
    n = int(onp.prod(shape))
    data = onp.frombuffer(r.read(n * dt.itemsize), dt).reshape(shape)
    return data, 0, [], shape


def load_bytes(buf):
    """Parse a reference .params byte string →
    (list of (values, stype, aux, shape), list of names)."""
    r = _Reader(buf)
    header = r.u64()
    if header != LIST_MAGIC:
        raise ValueError(f"bad .params header {header:#x}")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_ndarray(r) for _ in range(n)]
    nk = r.u64()
    names = []
    for _ in range(nk):
        ln = r.u64()
        names.append(r.read(ln).decode())
    return arrays, names


def _write_tshape(out, shape):
    out.append(struct.pack("<i", len(shape)))
    if shape:
        out.append(struct.pack(f"<{len(shape)}q", *shape))


def save_bytes(items, named=True):
    """items: list of (name, numpy | (values, logical_shape, stype, aux)).

    Returns the reference-format byte string.  ``named=False`` writes an
    empty key table (the reference's unnamed-list save)."""
    out = [struct.pack("<QQQ", LIST_MAGIC, 0, len(items))]
    for _, val in items:
        if isinstance(val, tuple):
            values, shape, stype, aux = val
            # sparse record: storage_shape first, then logical shape
            out.append(struct.pack("<I", V2_MAGIC))
            out.append(struct.pack("<i", stype))
            _write_tshape(out, values.shape)   # storage shape
            _write_tshape(out, shape)          # logical shape
            out.append(struct.pack("<ii", 1, 0))
            out.append(struct.pack("<i", _DT2FLAG[onp.dtype(values.dtype)]))
            for a in aux:
                out.append(struct.pack("<i", _DT2FLAG[onp.dtype(a.dtype)]))
                _write_tshape(out, a.shape)
            out.append(onp.ascontiguousarray(values).tobytes())
            for a in aux:
                out.append(onp.ascontiguousarray(a).tobytes())
        else:
            values = onp.asarray(val)
            out.append(struct.pack("<I", V2_MAGIC))
            out.append(struct.pack("<i", 0))
            _write_tshape(out, values.shape)
            out.append(struct.pack("<ii", 1, 0))
            out.append(struct.pack("<i", _DT2FLAG[onp.dtype(values.dtype)]))
            out.append(onp.ascontiguousarray(values).tobytes())
    if not named:
        out.append(struct.pack("<Q", 0))
    else:
        names = [name for name, _ in items]
        out.append(struct.pack("<Q", len(names)))
        for name in names:
            nb = name.encode()
            out.append(struct.pack("<Q", len(nb)))
            out.append(nb)
    return b"".join(out)
