"""Sparse NDArray storage types: row_sparse and csr.

Reference: include/mxnet/ndarray.h:61-82 storage types + src/operator
sparse kernels; kvstore pulls row_sparse shards (kvstore_dist.h:558).

TPU design decision (SURVEY.md §7 "Sparse storage"): the MXU has no
sparse gather/scatter path, so sparse arrays here are *index + values*
containers with the same API (``indices``, ``data``, ``tostype``,
arithmetic against dense) whose compute lowers to dense segment ops
(gather / scatter-add).  This keeps capability parity — row-sparse
gradients, sparse pull, sparse optimizer updates — with documented dense
fallback performance.
"""
from __future__ import annotations

import numpy as onp
import jax.numpy as jnp

from ..context import current_context
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros", "retain"]


def retain(data, indices):
    """Module-level sparse row retain (reference mx.nd.sparse.retain,
    src/operator/tensor/sparse_retain-inl.h): keep only the rows named
    by ``indices``; other rows become zero/unstored."""
    if isinstance(data, RowSparseNDArray):
        return data.retain(indices)
    raise TypeError("sparse.retain expects a RowSparseNDArray; got "
                    f"{type(data).__name__} (dense arrays: use "
                    "nd.sparse_retain)")


class BaseSparseNDArray(NDArray):
    """Common behavior: dense materialization via ``todense``."""

    __slots__ = ()

    @property
    def stype(self):
        raise NotImplementedError

    def todense(self) -> NDArray:
        return NDArray(self.data, ctx=self.ctx)

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        return cast_storage(self, stype)


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``values``; all other rows are zero."""

    __slots__ = ("_rs_indices", "_rs_values", "_dense_shape")

    def __init__(self, values, indices, shape, ctx=None):
        self._rs_indices = jnp.asarray(indices, jnp.int64 if False else jnp.int32)
        self._rs_values = jnp.asarray(values)
        self._dense_shape = tuple(shape)
        dense = jnp.zeros(shape, self._rs_values.dtype).at[self._rs_indices].set(
            self._rs_values)
        super().__init__(dense, ctx=ctx or current_context())

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        return NDArray(self._rs_indices, ctx=self.ctx)

    @property
    def values(self):
        return NDArray(self._rs_values, ctx=self.ctx)

    def retain(self, indices):
        """Keep only the given rows (reference sparse_retain op)."""
        idx = jnp.asarray(indices.data if isinstance(indices, NDArray) else indices,
                          jnp.int32)
        vals = self.data[idx]
        return RowSparseNDArray(vals, idx, self._dense_shape, ctx=self.ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr", "_dense_shape")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        self._csr_data = jnp.asarray(data)
        self._csr_indices = jnp.asarray(indices, jnp.int32)
        self._csr_indptr = jnp.asarray(indptr, jnp.int32)
        self._dense_shape = tuple(shape)
        dense = onp.zeros(shape, dtype=onp.asarray(self._csr_data).dtype)
        indptr_np = onp.asarray(self._csr_indptr)
        indices_np = onp.asarray(self._csr_indices)
        data_np = onp.asarray(self._csr_data)
        for row in range(shape[0]):
            lo, hi = indptr_np[row], indptr_np[row + 1]
            dense[row, indices_np[lo:hi]] = data_np[lo:hi]
        super().__init__(dense, ctx=ctx or current_context())

    @property
    def stype(self):
        return "csr"

    @property
    def indices(self):
        return NDArray(self._csr_indices, ctx=self.ctx)

    @property
    def indptr(self):
        return NDArray(self._csr_indptr, ctx=self.ctx)

    @property
    def data_array(self):
        return NDArray(self._csr_data, ctx=self.ctx)


def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 2:
        values, indices = arg
        values = values.data if isinstance(values, NDArray) else jnp.asarray(values)
        return RowSparseNDArray(values, indices, shape, ctx=ctx)
    dense = NDArray(arg, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx)
    dense = NDArray(arg, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """Dense ↔ sparse conversion (reference tensor/cast_storage-inl.h)."""
    if stype == "default":
        return NDArray(arr.data, ctx=arr.ctx)
    np_val = onp.asarray(arr.data)
    if stype == "row_sparse":
        nz_rows = onp.nonzero(np_val.reshape(np_val.shape[0], -1).any(axis=1))[0]
        return RowSparseNDArray(np_val[nz_rows], nz_rows, np_val.shape, ctx=arr.ctx)
    if stype == "csr":
        if np_val.ndim != 2:
            raise ValueError("csr requires 2-D")
        indptr = [0]
        indices, data = [], []
        for row in np_val:
            nz = onp.nonzero(row)[0]
            indices.extend(nz.tolist())
            data.extend(row[nz].tolist())
            indptr.append(len(indices))
        return CSRNDArray(onp.asarray(data, np_val.dtype), indices, indptr,
                          np_val.shape, ctx=arr.ctx)
    raise ValueError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype), jnp.zeros((0,), jnp.int32),
            shape, ctx=ctx)
    from . import zeros as dense_zeros
    return dense_zeros(shape, ctx=ctx, dtype=dtype)
