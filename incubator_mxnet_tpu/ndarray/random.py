"""Eager random samplers: ``nd.random.*`` (reference python/mxnet/ndarray/random.py).

Keys are drawn from the global stream (``mx.random.seed``); inside
hybridize tracing, keys derive from the CachedOp's key input so compiled
graphs stay pure (see random.py module docstring for the contract).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import random as _random
from ..context import current_context
from ..ops.registry import get_op
from .ndarray import NDArray

__all__ = ["uniform", "normal", "randn", "randint", "gamma", "exponential",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "bernoulli", "seed"]

seed = _random.seed


def _sample(op_name, shape, dtype, ctx, out, **params):
    op = get_op(op_name)
    shape = (shape,) if isinstance(shape, int) else tuple(shape or ())
    key = _random.next_key()
    data = op.fn(key, shape=shape, dtype=dtype, **params)
    nd = NDArray(data, ctx=ctx or current_context())
    if out is not None:
        out._set_data(nd.data)
        return out
    return nd


def _tensor_params(*vals):
    """Reference _random_helper dispatch (python/mxnet/ndarray/random.py:28):
    NDArray distribution params route to the per-element `sample_*` op,
    scalars to the plain `random_*` sampler.  Mixing the two is an error
    there and here."""
    kinds = [isinstance(v, NDArray) for v in vals]
    if all(kinds):
        return True
    if any(kinds):
        raise ValueError(
            "distribution params must be all scalars or all NDArrays")
    return False


def _sample_per_elem(op_name, params, shape, out, **kw):
    from . import __dict__ as _nd_ns  # the key-injecting nd wrappers
    fn = _nd_ns[op_name]
    return fn(*params, shape=shape, out=out, **kw)


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(low, high):
        return _sample_per_elem("sample_uniform", (low, high), shape,
                                out, dtype=dtype)
    return _sample("random_uniform", shape, dtype, ctx, out, low=low, high=high)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(loc, scale):
        return _sample_per_elem("sample_normal", (loc, scale), shape,
                                out, dtype=dtype)
    return _sample("random_normal", shape, dtype, ctx, out, loc=loc, scale=scale)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=(), dtype="int32", ctx=None, out=None):
    return _sample("random_randint", shape, dtype, ctx, out, low=low, high=high)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(alpha, beta):
        return _sample_per_elem("sample_gamma", (alpha, beta), shape,
                                out, dtype=dtype)
    return _sample("random_gamma", shape, dtype, ctx, out, alpha=alpha, beta=beta)


def exponential(scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(scale):
        return _sample_per_elem("sample_exponential", (1.0 / scale,), shape,
                                out, dtype=dtype)
    return _sample("random_exponential", shape, dtype, ctx, out, lam=1.0 / scale)


def poisson(lam=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(lam):
        return _sample_per_elem("sample_poisson", (lam,), shape, out,
                                dtype=dtype)
    return _sample("random_poisson", shape, dtype, ctx, out, lam=lam)


def negative_binomial(k=1, p=1.0, shape=(), dtype="float32", ctx=None, out=None):
    if _tensor_params(k, p):
        return _sample_per_elem("sample_negative_binomial", (k, p), shape,
                                out, dtype=dtype)
    return _sample("random_negative_binomial", shape, dtype, ctx, out, k=k, p=p)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(),
                                  dtype="float32", ctx=None, out=None):
    """Reference python/mxnet/ndarray/random.py generalized_negative_binomial."""
    if _tensor_params(mu, alpha):
        return _sample_per_elem("sample_generalized_negative_binomial",
                                (mu, alpha), shape, out, dtype=dtype)
    mu_nd = NDArray(jnp.full((), float(mu), jnp.float32))
    a_nd = NDArray(jnp.full((), float(alpha), jnp.float32))
    res = _sample_per_elem("sample_generalized_negative_binomial",
                           (mu_nd, a_nd), shape, out, dtype=dtype)
    return res


def bernoulli(p=0.5, shape=(), dtype="float32", ctx=None, out=None):
    return _sample("random_bernoulli", shape, dtype, ctx, out, p=p)


def multinomial(data, shape=(), get_prob=False, dtype="int32"):
    op = get_op("sample_multinomial")
    key = _random.next_key()
    out = op.fn(data.data, key, shape=shape, get_prob=get_prob)
    if get_prob:
        return NDArray(out[0], ctx=data.ctx), NDArray(out[1], ctx=data.ctx)
    return NDArray(out, ctx=data.ctx)


def shuffle(data, out=None):
    op = get_op("shuffle")
    key = _random.next_key()
    nd = NDArray(op.fn(data.data, key), ctx=data.ctx)
    if out is not None:
        out._set_data(nd.data)
        return out
    return nd
