"""Optimizer algorithms.

TPU-native counterpart of the reference optimizer suite
(python/mxnet/optimizer/, 3.5 kLoC + fused C++/CUDA update kernels in
src/operator/optimizer_op*.cc).  Each ``update`` is a pure jnp expression
— XLA fuses the whole update into one kernel, which is what the
reference's hand-fused ``multi_sgd_update``/``lamb_update_phase1`` kernels
achieved manually.  Multi-precision (fp32 master weights for bf16/fp16
params) follows the reference's ``multi_precision`` flag.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..base import registry
from ..ndarray import NDArray

__all__ = ["Optimizer", "Updater", "get_updater", "register", "create"]

_reg = registry("optimizer")


def register(cls):
    return _reg.register(cls)


def create(name, **kwargs):
    try:
        return _reg.create(name, **kwargs)
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}") from None


class Optimizer:
    """Base optimizer (reference optimizer/optimizer.py Optimizer).

    State is kept per-parameter-index like the reference (create_state /
    update(index, weight, grad, state)); the Trainer drives it.
    """

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = 0.01 if learning_rate is None else learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: dict[int, int] = {}
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult: dict = {}
        self.wd_mult: dict = {}

    # -- reference API ----------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler overwrites learning rate")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        if index in self.param_dict:
            lr *= getattr(self.param_dict[index], "lr_mult", 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= getattr(self.param_dict[index], "wd_mult", 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- to implement -----------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.data.dtype in (jnp.float16, jnp.bfloat16):
            master = NDArray(weight.data.astype(jnp.float32), ctx=weight.ctx)
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.data.dtype in (jnp.float16, jnp.bfloat16):
            master, mstate = state
            g32 = NDArray(grad.data.astype(jnp.float32), ctx=grad.ctx)
            self.update(index, master, g32, mstate)
            weight._set_data(master.data.astype(weight.data.dtype))
        else:
            self.update(index, weight, grad, state)

    # -- shared gradient preprocessing ------------------------------------
    def _prep(self, index, weight, grad):
        # count first: the scheduler sees the post-increment num_update
        # (reference Optimizer.update order)
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return lr, wd, g

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.learning_rate})"


@register
class SGD(Optimizer):
    """SGD with momentum and weight decay (reference optimizer/sgd.py).

    state = momentum buffer; update matches the reference formula:
    mom = momentum*mom - lr*(grad + wd*w); w += mom.
    """

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=True,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray) and self.lazy_update:
            return self._update_row_sparse(index, weight, grad, state)
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        g = g.astype(w.dtype) + wd * w
        if state is not None:
            mom = self.momentum * state.data - lr * g
            state._set_data(mom)
            weight._set_data(w + mom)
        else:
            weight._set_data(w - lr * g)

    def _update_row_sparse(self, index, weight, grad, state):
        """Lazy update: only rows present in the row_sparse gradient are
        touched (reference optimizer/sgd.py lazy_update + sgd-inl.h
        SGDUpdateRspRspImpl) — absent rows keep weight AND momentum
        unchanged, which differs from a dense update when momentum or wd
        is nonzero (documented reference semantics)."""
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        rows = jnp.asarray(grad._rs_indices, jnp.int32)
        g_rows = grad._rs_values * self.rescale_grad
        if self.clip_gradient is not None:
            g_rows = jnp.clip(g_rows, -self.clip_gradient,
                              self.clip_gradient)
        g_rows = g_rows.astype(weight.data.dtype)
        w = weight.data
        w_rows = w[rows]
        g_rows = g_rows + wd * w_rows
        if state is not None:
            mom_rows = self.momentum * state.data[rows] - lr * g_rows
            state._set_data(state.data.at[rows].set(mom_rows))
            weight._set_data(w.at[rows].add(mom_rows))
        else:
            weight._set_data(w.at[rows].add(-lr * g_rows))


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference optimizer/sgld.py)."""

    def update(self, index, weight, grad, state):
        import jax
        from .. import random as _random
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        noise = jax.random.normal(_random.next_key(), w.shape, jnp.float32) * \
            math.sqrt(lr)
        weight._set_data(w - lr / 2 * (g + wd * w) + noise.astype(w.dtype))


@register
class Signum(Optimizer):
    """signSGD with momentum (reference optimizer/sgd.py Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        if state is not None:
            mom = self.momentum * state.data - (1 - self.momentum) * (g + wd * w)
            state._set_data(mom)
            weight._set_data((1 - lr * self.wd_lh) * w + lr * jnp.sign(mom))
        else:
            weight._set_data((1 - lr * self.wd_lh) * w - lr * jnp.sign(g + wd * w))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer/dcasgd.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx) \
            if self.momentum != 0.0 else None
        prev = NDArray(weight.data + 0, ctx=weight.ctx)
        return (mom, prev)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        mom, prev = state
        w = weight.data
        comp = g + wd * w + self.lamda * g * g * (w - prev.data)
        if mom is not None:
            m = self.momentum * mom.data - lr * comp
            mom._set_data(m)
            new_w = w + m
        else:
            new_w = w - lr * comp
        prev._set_data(new_w)
        weight._set_data(new_w)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference optimizer/nag.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        g = g + wd * w
        if state is not None:
            mom = self.momentum * state.data + g
            state._set_data(mom)
            weight._set_data(w - lr * (g + self.momentum * mom))
        else:
            weight._set_data(w - lr * g)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        hist = state.data + g * g
        state._set_data(hist)
        weight._set_data(w - lr * (g / jnp.sqrt(hist + self.float_stable_eps)
                                   + wd * w))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        _, wd, g = self._prep(index, weight, grad)
        acc_g, acc_delta = state
        w = weight.data
        g = g + wd * w
        new_acc_g = self.rho * acc_g.data + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_delta.data + self.epsilon) / \
            jnp.sqrt(new_acc_g + self.epsilon) * g
        new_acc_delta = self.rho * acc_delta.data + (1 - self.rho) * delta * delta
        acc_g._set_data(new_acc_g)
        acc_delta._set_data(new_acc_delta)
        weight._set_data(w - delta)


@register
class Adam(Optimizer):
    """Adam (reference optimizer/adam.py) with bias correction."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        m, v = state
        w = weight.data
        g = g + wd * w
        new_m = self.beta1 * m.data + (1 - self.beta1) * g
        new_v = self.beta2 * v.data + (1 - self.beta2) * g * g
        m._set_data(new_m)
        v._set_data(new_v)
        coef = lr * math.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        weight._set_data(w - coef * new_m / (jnp.sqrt(new_v) + self.epsilon))


@register
class AdamW(Adam):
    """Decoupled weight decay Adam (reference contrib adamw.py)."""

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        m, v = state
        w = weight.data
        new_m = self.beta1 * m.data + (1 - self.beta1) * g
        new_v = self.beta2 * v.data + (1 - self.beta2) * g * g
        m._set_data(new_m)
        v._set_data(new_v)
        coef = lr * math.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        weight._set_data(w - coef * new_m / (jnp.sqrt(new_v) + self.epsilon)
                         - lr * wd * w)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        m, u = state
        w = weight.data
        g = g + wd * w
        new_m = self.beta1 * m.data + (1 - self.beta1) * g
        new_u = jnp.maximum(self.beta2 * u.data, jnp.abs(g))
        m._set_data(new_m)
        u._set_data(new_u)
        weight._set_data(w - lr / (1 - self.beta1 ** t) * new_m / (new_u + 1e-8))


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        m, v = state
        w = weight.data
        g = g + wd * w
        mom_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mom_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= mom_t
        m_sched_next = self.m_schedule * mom_t1
        g_prime = g / (1 - self.m_schedule)
        new_m = self.beta1 * m.data + (1 - self.beta1) * g
        new_v = self.beta2 * v.data + (1 - self.beta2) * g * g
        m._set_data(new_m)
        v._set_data(new_v)
        m_prime = new_m / (1 - m_sched_next)
        v_prime = new_v / (1 - self.beta2 ** t)
        m_bar = (1 - mom_t) * g_prime + mom_t1 * m_prime
        weight._set_data(w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon))


@register
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        z, n = state
        new_n = n.data + g * g
        sigma = (jnp.sqrt(new_n) - jnp.sqrt(n.data)) / lr
        new_z = z.data + g - sigma * weight.data
        z._set_data(new_z)
        n._set_data(new_n)
        new_w = jnp.where(
            jnp.abs(new_z) > self.lamda1,
            -(new_z - jnp.sign(new_z) * self.lamda1) /
            ((self.beta + jnp.sqrt(new_n)) / lr + wd),
            jnp.zeros_like(weight.data))
        weight._set_data(new_w)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        d, v, z = state
        w = weight.data
        g = g + wd * w
        new_v = self.beta2 * v.data + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * \
            (jnp.sqrt(new_v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d.data
        new_z = self.beta1 * z.data + (1 - self.beta1) * g - sigma * w
        d._set_data(d_t)
        v._set_data(new_v)
        z._set_data(new_z)
        weight._set_data(-new_z / d_t)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference optimizer/lars.py)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        w_norm = jnp.linalg.norm(w.reshape(-1))
        g_norm = jnp.linalg.norm(g.reshape(-1))
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            jnp.ones(()))
        g = (g + wd * w) * trust
        if state is not None:
            mom = self.momentum * state.data - lr * g
            state._set_data(mom)
            weight._set_data(w + mom)
        else:
            weight._set_data(w - lr * g)


@register
class LAMB(Optimizer):
    """Layer-wise Adam for large batches (reference optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        return (z(), z())

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        t = self._index_update_count[index]
        m, v = state
        w = weight.data
        new_m = self.beta1 * m.data + (1 - self.beta1) * g
        new_v = self.beta2 * v.data + (1 - self.beta2) * g * g
        m._set_data(new_m)
        v._set_data(new_v)
        mh, vh = new_m, new_v
        if self.bias_correction:
            mh = new_m / (1 - self.beta1 ** t)
            vh = new_v / (1 - self.beta2 ** t)
        r = mh / (jnp.sqrt(vh) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w.reshape(-1))
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        r_norm = jnp.linalg.norm(r.reshape(-1))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm,
                          jnp.ones(()))
        weight._set_data(w - lr * ratio * r)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        lr, wd, g = self._prep(index, weight, grad)
        w = weight.data
        g = g + wd * w
        if self.centered:
            n, mg, delta = state
            new_n = (1 - self.gamma1) * g * g + self.gamma1 * n.data
            new_mg = (1 - self.gamma1) * g + self.gamma1 * mg.data
            new_delta = self.gamma2 * delta.data - \
                lr * g / jnp.sqrt(new_n - new_mg * new_mg + self.epsilon)
            n._set_data(new_n)
            mg._set_data(new_mg)
            delta._set_data(new_delta)
            new_w = w + new_delta
        else:
            (n,) = state
            new_n = (1 - self.gamma1) * g * g + self.gamma1 * n.data
            n._set_data(new_n)
            # sqrt(n) + eps, matching rmsprop_update (optimizer_op-inl.h:2025)
            new_w = w - lr * g / (jnp.sqrt(new_n) + self.epsilon)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        weight._set_data(new_w)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style adaptive rates (reference lbsgd.py).

    Kept as SGD + warmup semantics; layer-wise scaling handled by LARS."""

    def __init__(self, learning_rate=0.01, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(learning_rate=learning_rate, momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy


@register
class Test(Optimizer):
    """Reference parity: trivial optimizer used by tests (optimizer.py Test)."""

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        weight._set_data(weight.data + grad.data * self.rescale_grad)
        state._set_data(weight.data)


# alias names matching the reference string registry
_reg.alias("sgd")(SGD)
_reg.alias("sgld")(SGLD)
_reg.alias("signum")(Signum)
_reg.alias("dcasgd")(DCASGD)
_reg.alias("nag")(NAG)
_reg.alias("adagrad")(AdaGrad)
_reg.alias("adadelta")(AdaDelta)
_reg.alias("adam")(Adam)
_reg.alias("adamw")(AdamW)
_reg.alias("adamax")(Adamax)
_reg.alias("nadam")(Nadam)
_reg.alias("ftrl")(FTRL)
_reg.alias("ftml")(FTML)
_reg.alias("lars")(LARS)
_reg.alias("lamb")(LAMB)
_reg.alias("rmsprop")(RMSProp)
_reg.alias("lbsgd")(LBSGD)


class Updater:
    """Applies an optimizer by index (reference optimizer/updater.py)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: dict = {}
        self.states_synced: dict = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle
        states = {
            k: (v.asnumpy() if isinstance(v, NDArray) else
                tuple(s.asnumpy() if isinstance(s, NDArray) else s for s in v)
                if isinstance(v, tuple) else v)
            for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer else states)

    def set_states(self, states_bytes):
        import pickle
        data = pickle.loads(states_bytes)
        if isinstance(data, tuple):
            states, self.optimizer = data
        else:
            states = data

        def restore(v, like):
            if isinstance(v, tuple):
                return tuple(restore(s, None) for s in v)
            if v is None:
                return None
            return NDArray(v)

        self.states = {k: restore(v, None) for k, v in states.items()}


def get_updater(optimizer):
    return Updater(optimizer)
