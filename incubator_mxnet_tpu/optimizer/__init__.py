"""Optimizers (reference python/mxnet/optimizer/ — 17 algorithms)."""
from .optimizer import (
    Optimizer, Updater, get_updater, register, create,
    SGD, SGLD, Signum, DCASGD, NAG, AdaGrad, AdaDelta, Adam, AdamW, Adamax,
    Nadam, FTRL, FTML, LARS, LAMB, RMSProp, LBSGD, Test,
)
from . import lr_scheduler
from .lr_scheduler import LRScheduler
