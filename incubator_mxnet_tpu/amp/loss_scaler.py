"""Dynamic loss scaler (reference contrib/amp/loss_scaler.py)."""
from __future__ import annotations


class LossScaler:
    """Doubles the scale every ``scale_window`` overflow-free steps and
    halves it on overflow — the reference's dynamic scaling policy."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite.

        Device-side: one fused multi_all_finite reduction over every
        gradient and a single scalar readback (reference
        optimizer_op.cc multi_all_finite), instead of pulling each
        gradient to the host.
        """
        from ..ops.registry import invoke
        grads = [p.grad() for p in params
                 if p.grad_req != "null" and p._data is not None
                 and p._data.grad is not None]
        if not grads:
            return False
        flag = invoke("multi_all_finite", *grads, num_arrays=len(grads))
        return float(flag.asnumpy()[0]) == 0.0

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
        return self.loss_scale
