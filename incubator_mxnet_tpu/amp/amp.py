"""AMP entry points (reference contrib/amp/amp.py:47-389).

Two conversion mechanisms, both driven by the op lists in ``lists.py``:

* **Eager / Gluon path** — ``convert_block`` casts parameters and
  attaches a ``CastPolicy`` to the block; every op executed under the
  block's forward (eager, hybridized, or via ``Block.functional``) has
  its floating inputs cast per-op inside ``ops.registry.invoke``.  This
  is the analog of the reference's ``convert_hybrid_block``
  (contrib/amp/amp.py:550) where the casts live in the converted graph.
* **Symbolic path** — ``convert_symbol`` rewrites the Symbol DAG,
  inserting explicit ``amp_cast``/``amp_multicast`` nodes
  (reference amp.py:389 convert_symbol → C++ ReducePrecision pass,
  src/nnvm/low_precision_pass.cc).  ``convert_model`` additionally casts
  the parameter dict.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..base import dtype_from_any
from .loss_scaler import LossScaler
from . import lists

_state = {"initialized": False, "dtype": None, "scaler": None}

_tls = threading.local()


def init(target_dtype="bfloat16"):
    """Enable mixed precision (reference amp.py:47 init).

    bfloat16 (TPU native): params stay fp32-master-on-demand, compute in
    bf16 via block casting; no loss scaling needed.  float16: enables the
    dynamic LossScaler.
    """
    _state["initialized"] = True
    _state["dtype"] = dtype_from_any(target_dtype)
    if target_dtype in ("float16", "fp16"):
        _state["scaler"] = LossScaler()
    return _state


def init_trainer(trainer):
    """Attach the loss scaler to a Trainer (reference amp.py init_trainer)."""
    trainer._amp_loss_scaler = _state.get("scaler")
    return trainer


# ---------------------------------------------------------------------------
# CastPolicy: list-driven per-op input casting on the eager invoke path
# ---------------------------------------------------------------------------

class CastPolicy:
    """Per-op dtype decisions compiled from the amp lists.

    ``cast_args(op_name, arrays)`` returns the arrays with floating
    inputs cast per the op's class: lp16 ops to the low-precision target,
    fp32 ops to float32, widest-type ops to the widest floating dtype
    among the inputs.  Non-floating arrays (int labels, bool masks) pass
    through untouched, as do ops in no list.
    """

    def __init__(self, target_dtype="bfloat16", target_dtype_ops=None,
                 fp32_ops=None, widest_dtype_ops=None, excluded_ops=None):
        self.target_dtype = dtype_from_any(target_dtype)
        lp16, fp32, widest = lists.get_lists(target_dtype)
        self.lp16 = set(lp16 if target_dtype_ops is None else target_dtype_ops)
        self.fp32 = set(fp32 if fp32_ops is None else fp32_ops)
        self.widest = set(widest if widest_dtype_ops is None
                          else widest_dtype_ops)
        self.excluded = set(excluded_ops or ())
        overlap = self.lp16 & self.fp32
        if overlap:
            raise ValueError(
                f"ops cannot be in both the target-dtype and fp32 lists: "
                f"{sorted(overlap)}")

    def op_class(self, op_name):
        if op_name in self.excluded:
            return None
        if op_name in self.lp16:
            return "lp16"
        if op_name in self.fp32:
            return "fp32"
        if op_name in self.widest:
            return "widest"
        return None

    def cast_args(self, op_name, arrays):
        cls = self.op_class(op_name)
        if cls is None:
            return arrays

        def is_float(a):
            return hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                          jnp.floating)

        if cls == "lp16":
            tgt = self.target_dtype
            return [a.astype(tgt) if is_float(a) and a.dtype != tgt else a
                    for a in arrays]
        if cls == "fp32":
            return [a.astype(jnp.float32)
                    if is_float(a) and a.dtype != jnp.float32 else a
                    for a in arrays]
        floats = [a.dtype for a in arrays if is_float(a)]
        if not floats:
            return arrays
        widest = max(floats, key=lambda d: jnp.finfo(d).bits)
        return [a.astype(widest) if is_float(a) and a.dtype != widest else a
                for a in arrays]


def current_policy():
    return getattr(_tls, "policy", None)


@contextlib.contextmanager
def policy_scope(policy):
    prev = getattr(_tls, "policy", None)
    _tls.policy = policy
    try:
        yield policy
    finally:
        _tls.policy = prev


# ---------------------------------------------------------------------------
# Block conversion (eager path)
# ---------------------------------------------------------------------------

_KEEP_FP32_SUFFIXES = ("gamma", "beta", "running_mean", "running_var",
                       "moving_mean", "moving_var")


def convert_block(block, target_dtype="bfloat16", target_dtype_ops=None,
                  fp32_ops=None, widest_dtype_ops=None, excluded_ops=None):
    """Convert a Block to mixed precision (reference convert_hybrid_block).

    Casts the block's parameters to ``target_dtype`` (norm-layer
    scale/offset and moving statistics stay fp32) and attaches a
    ``CastPolicy`` built from the amp lists — honored per-op on every
    forward through the block, so ``fp32_ops=['softmax']`` really does
    run softmax in fp32 on bf16 activations.
    """
    policy = CastPolicy(target_dtype, target_dtype_ops=target_dtype_ops,
                        fp32_ops=fp32_ops, widest_dtype_ops=widest_dtype_ops,
                        excluded_ops=excluded_ops)
    for name, p in block.collect_params().items():
        if name.endswith(_KEEP_FP32_SUFFIXES):
            continue
        p.cast(target_dtype)
    block._amp_policy = policy
    return block


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    scaled = loss * scaler.loss_scale
    trainer._scale = 1.0 / scaler.loss_scale
    yield scaled
    overflow = scaler.has_overflow(trainer._params)
    scaler.update_scale(overflow)
    trainer._amp_skip_update = overflow


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is not None:
        trainer._scale = 1.0


# ---------------------------------------------------------------------------
# Symbol conversion (graph rewrite, reference amp.py:389 convert_symbol)
# ---------------------------------------------------------------------------

def convert_symbol(sym, target_dtype="bfloat16", target_dtype_ops=None,
                   fp32_ops=None, widest_dtype_ops=None, excluded_sym_names=None,
                   data_names=None):
    """Rewrite a Symbol graph with explicit amp_cast/amp_multicast nodes.

    Every op in the target-dtype list gets its floating inputs wrapped in
    ``amp_cast(dtype=target)``; fp32-list ops get ``amp_cast(float32)``;
    widest-list ops with mixed-precision inputs get one ``amp_multicast``
    over all inputs.  Ops named in ``excluded_sym_names`` are left alone.
    Returns a new Symbol; the input symbol is not mutated.
    """
    from ..symbol import Symbol, _SymNode

    policy = CastPolicy(target_dtype, target_dtype_ops=target_dtype_ops,
                        fp32_ops=fp32_ops, widest_dtype_ops=widest_dtype_ops)
    excluded = set(excluded_sym_names or ())
    tgt_name = jnp.dtype(policy.target_dtype).name

    old2new: dict[int, _SymNode] = {}
    cast_cache: dict[tuple, _SymNode] = {}

    def cast_edge(entry, dtype_name):
        """Wrap an input edge in an amp_cast node.

        Aux-state variables (BatchNorm moving stats) are never cast: the
        reference's ReducePrecision pass leaves aux inputs alone, and the
        executor identifies aux updates by matching direct variable
        inputs.  Casts dedup per (producer edge, dtype) so a tensor
        feeding N listed ops is cast once, with a unique node name.
        """
        if entry.op_name is None and entry.attrs.get("__aux__"):
            return entry
        key = (entry.key, entry.output_index, dtype_name)
        cast = cast_cache.get(key)
        if cast is None:
            cast = _SymNode("amp_cast",
                            f"{entry.name}_amp_cast_{dtype_name}"
                            + (f"_{entry.output_index}"
                               if entry.output_index else ""),
                            [entry], {"dtype": dtype_name})
            cast_cache[key] = cast
        return cast

    order = sym._topo_order()
    for node in order:
        if node.op_name is None:
            old2new[node.key] = _SymNode(None, node.name, [], {},
                                         attrs=dict(node.attrs))
            continue
        new_inputs = [old2new[i.key].clone_for_output(i.output_index)
                      for i in node.inputs]
        cls = None if node.name in excluded else policy.op_class(node.op_name)
        if cls == "lp16":
            new_inputs = [cast_edge(e, tgt_name) for e in new_inputs]
        elif cls == "fp32":
            new_inputs = [cast_edge(e, "float32") for e in new_inputs]
        elif cls == "widest" and len(new_inputs) > 1:
            multi = _SymNode("amp_multicast", f"{node.name}_amp_multicast",
                             new_inputs, {"num_outputs": len(new_inputs)},
                             num_outputs=len(new_inputs))
            new_inputs = [multi.clone_for_output(i)
                          for i in range(len(new_inputs))]
        old2new[node.key] = _SymNode(node.op_name, node.name, new_inputs,
                                     dict(node.kwargs),
                                     attrs=dict(node.attrs),
                                     num_outputs=node.num_outputs)

    heads = [old2new[n.key].clone_for_output(n.output_index)
             for n in sym._head_entries()]
    return Symbol(heads)


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None, widest_dtype_ops=None,
                  excluded_sym_names=None, cast_optional_params=False):
    """convert_symbol + cast the parameter dicts (reference amp.py:477).

    Parameters feeding only lp16 ops may be stored in the low-precision
    dtype when ``cast_optional_params`` (saves checkpoint bytes); by
    default params stay fp32 and the graph's amp_cast nodes downcast at
    runtime, matching the reference default.
    """
    new_sym = convert_symbol(sym, target_dtype, target_dtype_ops, fp32_ops,
                             widest_dtype_ops, excluded_sym_names)
    tgt = dtype_from_any(target_dtype)
    arg_params = dict(arg_params)
    aux_params = dict(aux_params)
    if cast_optional_params:
        policy = CastPolicy(target_dtype, target_dtype_ops=target_dtype_ops,
                            fp32_ops=fp32_ops,
                            widest_dtype_ops=widest_dtype_ops)
        # a param may be cast when every consumer is an lp16-class op
        # that is not excluded by name (an excluded op stays fp32, so its
        # params must too)
        excluded = set(excluded_sym_names or ())
        ok: dict[str, bool] = {}
        for node in sym._topo_order():
            if node.op_name is None:
                continue
            is_lp16 = (node.name not in excluded
                       and policy.op_class(node.op_name) == "lp16")
            for i in node.inputs:
                if i.op_name is None:
                    ok[i.name] = ok.get(i.name, True) and is_lp16
        for name, val in list(arg_params.items()):
            if ok.get(name, False):
                arg_params[name] = val.astype(tgt)
    return new_sym, arg_params, aux_params
