"""AMP entry points (reference contrib/amp/amp.py:47-389)."""
from __future__ import annotations

import contextlib

from ..base import dtype_from_any
from .loss_scaler import LossScaler

_state = {"initialized": False, "dtype": None, "scaler": None}


def init(target_dtype="bfloat16"):
    """Enable mixed precision (reference amp.py:47 init).

    bfloat16 (TPU native): params stay fp32-master-on-demand, compute in
    bf16 via block casting; no loss scaling needed.  float16: enables the
    dynamic LossScaler.
    """
    _state["initialized"] = True
    _state["dtype"] = dtype_from_any(target_dtype)
    if target_dtype in ("float16", "fp16"):
        _state["scaler"] = LossScaler()
    return _state


def init_trainer(trainer):
    """Attach the loss scaler to a Trainer (reference amp.py init_trainer)."""
    trainer._amp_loss_scaler = _state.get("scaler")
    return trainer


def convert_block(block, target_dtype="bfloat16", fp32_ops=None):
    """Cast a Block's parameters to the low-precision dtype, keeping
    norm-layer scale/offset params in fp32 (reference convert_model
    behavior via cast lists)."""
    from . import lists
    keep_fp32_suffixes = ("gamma", "beta", "running_mean", "running_var",
                          "moving_mean", "moving_var")
    for name, p in block.collect_params().items():
        if name.endswith(keep_fp32_suffixes):
            continue
        p.cast(target_dtype)
    return block


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    scaled = loss * scaler.loss_scale
    trainer._scale = 1.0 / scaler.loss_scale
    yield scaled
    overflow = scaler.has_overflow(trainer._params)
    scaler.update_scale(overflow)
    trainer._amp_skip_update = overflow


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is not None:
        trainer._scale = 1.0
