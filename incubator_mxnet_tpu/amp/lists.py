"""Op cast lists (reference contrib/amp/lists/symbol_fp16.py).

Three classes, mirroring the reference's allow/deny structure:
* LP16_FUNCS — always run in low precision (MXU-bound matmul/conv)
* FP32_FUNCS — numerically sensitive, keep fp32
* WIDEST_TYPE_CASTS — follow the widest input type
"""

LP16_FUNCS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "matmul", "linalg_gemm2", "RNN", "dot_product_attention",
]

FP32_FUNCS = [
    "softmax", "log_softmax", "SoftmaxOutput", "BatchNorm", "LayerNorm",
    "GroupNorm", "InstanceNorm", "RMSNorm", "norm", "mean", "sum", "exp",
    "log", "erfinv", "power", "ctc_loss", "logsumexp", "var", "std",
]

WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "where",
    "concat", "stack",
]
