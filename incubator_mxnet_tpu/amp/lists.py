"""Op cast lists (reference contrib/amp/lists/symbol_fp16.py and
symbol_bf16.py).

Three classes per target dtype, mirroring the reference's structure:

* ``*_LP16`` — always run in the low-precision dtype (MXU-bound
  matmul/conv: the FLOPs live here, and bf16/fp16 inputs double the MXU
  throughput).
* ``*_FP32`` — numerically sensitive, keep fp32 (exp/log-heavy math,
  loss ops; for fp16 also the norm layers, whose variance computation
  overflows fp16's 5-bit exponent).
* ``*_WIDEST`` — follow the widest floating input type (elementwise
  combiners where silently downcasting one side loses information).

Ops in no list run in whatever dtype their inputs already have.  Note
the bf16 lists are more aggressive than fp16: bf16 shares fp32's
exponent range so the norm layers stay unlisted — their kernels in
``ops/nn_ops.py`` already accumulate statistics in fp32 internally while
keeping the normalize/affine math in the activation dtype.

Consumed by ``amp.CastPolicy`` (eager/Gluon path, applied per-op inside
``ops.registry.invoke``) and ``amp.convert_symbol`` (graph rewrite
inserting explicit ``amp_cast``/``amp_multicast`` nodes).
"""

# ---- shared op families ---------------------------------------------------

_MATMUL_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "matmul", "linalg_gemm2", "RNN", "dot_product_attention",
]

_SENSITIVE_OPS = [
    "softmax", "log_softmax", "SoftmaxOutput", "softmax_cross_entropy",
    "norm", "mean", "sum", "exp", "log", "log2", "log10", "log1p",
    "erfinv", "power", "ctc_loss", "logsumexp", "var", "std", "cumsum",
    "SoftmaxActivation", "MakeLoss",
]

_NORM_OPS = [
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "RMSNorm",
    "L2Normalization",
]

_WIDEST_OPS = [
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "where",
    "concat", "stack", "elemwise_add", "elemwise_sub", "elemwise_mul",
    "elemwise_div", "broadcast_add", "broadcast_sub", "broadcast_mul",
    "broadcast_div",
]

# ---- fp16 (reference lists/symbol_fp16.py) --------------------------------

FP16_LP16 = list(_MATMUL_OPS)
FP16_FP32 = list(_SENSITIVE_OPS) + list(_NORM_OPS)
FP16_WIDEST = list(_WIDEST_OPS)

# ---- bf16 (reference lists/symbol_bf16.py) --------------------------------

BF16_LP16 = list(_MATMUL_OPS)
BF16_FP32 = list(_SENSITIVE_OPS)
BF16_WIDEST = list(_WIDEST_OPS)

# Back-compat aliases (round-2 names; fp16 semantics)
LP16_FUNCS = FP16_LP16
FP32_FUNCS = FP16_FP32
WIDEST_TYPE_CASTS = FP16_WIDEST


def get_lists(target_dtype):
    """(lp16, fp32, widest) op lists for a target low-precision dtype."""
    name = str(target_dtype)
    if "bfloat16" in name:
        return BF16_LP16, BF16_FP32, BF16_WIDEST
    return FP16_LP16, FP16_FP32, FP16_WIDEST
