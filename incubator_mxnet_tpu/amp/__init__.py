"""Automatic mixed precision (reference python/mxnet/contrib/amp/).

TPU story: bf16 is the native MXU compute type and needs no loss scaling
(same exponent range as fp32), so ``amp.init(dtype='bfloat16')`` is the
default and the reference's fp16 + dynamic LossScaler machinery
(loss_scaler.py) is kept for API parity / fp16 experiments.
"""
from .amp import (init, init_trainer, convert_block, convert_symbol,
                  convert_model, scale_loss, unscale, CastPolicy,
                  current_policy, policy_scope)
from .loss_scaler import LossScaler
from . import lists
