"""Deterministic fault injection + shared retry machinery.

The reference framework's resilience story lives in ps-lite (van
resends, server retry queues) and in checkpoint-restart
(event_handler.py); neither is testable without a way to *make* faults
happen on demand.  This module is that harness: a registry of named
injection points threaded through the distributed and persistence
subsystems, configured entirely from the environment so CI can run the
same test suite with and without chaos.

Injection points (each named where the fault physically occurs):

* ``kvstore.send``      — worker→server request about to hit the wire
* ``kvstore.recv``      — worker waiting on the server response
* ``kvstore.heartbeat`` — a liveness probe / membership beat leaving
  the worker (one-shot budget; a lost beat burns heartbeat budget)
* ``engine.push``       — a closure being scheduled on the engine
* ``checkpoint.write``  — a shard file about to be written
* ``checkpoint.read``   — a shard file about to be read back (restore
  and reshard-restore verify CRCs against exactly these bytes)
* ``io.next_batch``     — the data pipeline handing out a batch
* ``serving.enqueue``   — an inference request entering a model queue
* ``serving.execute``   — a coalesced batch about to run on the device
* ``serving.route``     — the fleet router about to place a request on
  a replica (lost/slow routing hop; failover path)
* ``serving.probe``     — an active health probe about to hit a
  replica's ``/healthz`` (lost probes burn the health budget)
* ``serving.replica_exec`` — a replica about to execute a routed
  request (replica-side crash/stall; absorbed by failover)
* ``serving.session_step`` — a continuous-batching decode step about
  to run over the active sessions' stacked carries (transient faults
  retried by ``fault.retry``; a permanent fault surfaces to every
  stream riding the step)
* ``serving.session_snapshot`` — a session's carry about to be
  snapshotted to its CRC'd checkpoint dir (failures are counted and
  retried at the next period — a snapshot fault must never break the
  live stream, only widen the migration re-base window)
* ``serving.stream_write`` — a chunked-response chunk about to be
  written to the client socket (a fault here is a client-side
  connection loss: the stream is cancelled and counted)
* ``serving.scale``     — the autoscaler about to apply one scale
  decision (spawn/shrink a replica, load/unload/evict a model).  A
  transient fault drops that decision for the tick — the control
  loop re-evaluates and retries next tick; a delay models a slow
  control plane lagging behind the load signal
* ``serving.router_lease`` — a router about to publish its HA lease
  beat to the shared membership store (``serving/routerha.py``).  A
  lost beat ages the lease; enough in a row and the router's lease
  expires, handing its session affinities to the survivors — exactly
  the takeover path the ``routerha`` chaos stage drives
* ``serving.router_forward`` — a mis-hashed session request about to
  be forwarded to its ring-owning peer router (the ``X-MXNET-ROUTER``
  hop).  A delay models a slow peer hop; an error is a lost forward
  (surfaced typed — the hop budget bounds the loop either way)
* ``trainer.step``      — an elastic trainer step about to run (the
  eviction-notice / checkpoint-on-evict path)
* ``loadgen.tick``      — the soak harness's incident scheduler about
  to poll its virtual clock (serving/loadgen).  A delay models a late
  incident injector (chaos landing mid-recovery); an error perturbs a
  tick without skipping the incident

Spec grammar (``MXNET_FAULT_SPEC``)::

    spec    := entry (',' entry)*
    entry   := point ':' kind (':' key '=' value)*
    kind    := 'error' | 'delay'
    keys    := p      fire probability          (default 1.0)
               seed   per-point RNG seed        (default 0)
               ms     delay duration, ms        (delay only, default 100)
               n      max total fires           (default unlimited)
               after  calls to skip first       (default 0)
               class  'transient' | 'permanent' (error only, default
                      transient)

Example::

    MXNET_FAULT_SPEC='kvstore.send:error:p=0.05:seed=7,checkpoint.write:delay:ms=200'

Every point draws from its **own** ``random.Random(seed)`` so whether
call *k* at one point fires never depends on traffic at another point —
a chaos run is replayable from the spec alone.

Error taxonomy: :class:`TransientFault` derives from
``ConnectionError`` (the canonical retryable transport failure — the
PSClient reconnect path and :func:`retry` treat it like a real broken
socket); :class:`PermanentFault` derives from ``RuntimeError`` only and
must surface to the caller.
"""
from __future__ import annotations

import random
import threading
import time

from .base import get_env
from .locks import named_lock

__all__ = [
    "FaultInjected", "TransientFault", "PermanentFault",
    "parse_spec", "configure", "reset", "inject", "active_points",
    "declared_points", "stats", "retry",
]

#: Central injection-point registry: THE authoritative list of names a
#: ``fault.inject(...)`` call site or an ``MXNET_FAULT_SPEC`` entry may
#: use.  mxlint's MX-FAULT rules statically cross-check this tuple
#: against every ``inject`` call site (an undeclared point is a typo
#: that silently never fires; a declared-but-unwired point is dead
#: chaos coverage), and :func:`inject` enforces it at runtime whenever
#: a spec is active.  Add the name HERE when adding an injection point.
POINTS = ("kvstore.send", "kvstore.recv", "kvstore.heartbeat",
          "engine.push", "checkpoint.write", "checkpoint.read",
          "io.next_batch", "serving.enqueue", "serving.execute",
          "serving.route", "serving.probe", "serving.replica_exec",
          "serving.session_step", "serving.session_snapshot",
          "serving.stream_write", "serving.scale",
          "serving.router_lease", "serving.router_forward",
          "trainer.step", "loadgen.tick")

_POINT_SET = frozenset(POINTS)


def declared_points() -> tuple:
    """The registered injection-point names (static registry)."""
    return POINTS


class FaultInjected(Exception):
    """Marker base for injected faults (``isinstance`` lets handlers
    distinguish harness faults from organic ones in assertions)."""


class TransientFault(FaultInjected, ConnectionError):
    """Injected fault the caller is expected to retry away."""


class PermanentFault(FaultInjected, RuntimeError):
    """Injected fault that must surface: retry layers re-raise it."""


class _Point:
    __slots__ = ("name", "kind", "p", "seed", "ms", "limit", "after",
                 "permanent", "calls", "fired", "_rng", "_lock")

    def __init__(self, name, kind, p=1.0, seed=0, ms=100.0, limit=None,
                 after=0, permanent=False):
        self.name = name
        self.kind = kind
        self.p = float(p)
        self.seed = int(seed)
        self.ms = float(ms)
        self.limit = limit
        self.after = int(after)
        self.permanent = permanent
        self.calls = 0
        self.fired = 0
        self._rng = random.Random(self.seed)
        self._lock = named_lock("fault.point")

    def should_fire(self):
        with self._lock:
            self.calls += 1
            if self.calls <= self.after:
                return False
            if self.limit is not None and self.fired >= self.limit:
                return False
            if self.p < 1.0 and self._rng.random() >= self.p:
                return False
            self.fired += 1
            return True


def parse_spec(spec: str) -> dict:
    """Parse a ``MXNET_FAULT_SPEC`` string into {point: _Point}."""
    points = {}
    for raw in filter(None, (e.strip() for e in spec.split(","))):
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault spec entry {raw!r}: want 'point:kind[:k=v...]'")
        name, kind = parts[0], parts[1]
        if name not in POINTS:
            raise ValueError(
                f"fault spec names unknown point {name!r} (known: "
                f"{', '.join(POINTS)})")
        if kind not in ("error", "delay"):
            raise ValueError(
                f"fault spec entry {raw!r}: kind must be 'error' or "
                f"'delay', got {kind!r}")
        kw = {}
        for opt in parts[2:]:
            k, sep, v = opt.partition("=")
            if not sep:
                raise ValueError(
                    f"fault spec option {opt!r} in {raw!r}: want 'k=v'")
            if k == "p":
                kw["p"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "ms":
                kw["ms"] = float(v)
            elif k == "n":
                kw["limit"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "class":
                if v not in ("transient", "permanent"):
                    raise ValueError(
                        f"fault class must be transient|permanent, got {v!r}")
                kw["permanent"] = v == "permanent"
            else:
                raise ValueError(
                    f"unknown fault spec option {k!r} in {raw!r}")
        points[name] = _Point(name, kind, **kw)
    return points


_lock = named_lock("fault.registry")
_points: dict | None = None   # None = env not consulted yet


def _active() -> dict:
    global _points
    if _points is None:
        with _lock:
            if _points is None:
                spec = get_env("MXNET_FAULT_SPEC", "")
                _points = parse_spec(spec) if spec else {}
    return _points


def configure(spec: str | None):
    """Install a spec programmatically (tests); overrides the env."""
    global _points
    with _lock:
        _points = parse_spec(spec) if spec else {}


def reset():
    """Forget any configuration; next :func:`inject` re-reads the env."""
    global _points
    with _lock:
        _points = None


def active_points() -> dict:
    """The live {point: _Point} table (parsing the env on first use)."""
    return dict(_active())


def stats() -> dict:
    """Per-point {name: (calls, fired)} counters for assertions."""
    return {p.name: (p.calls, p.fired) for p in _active().values()}


def inject(point: str, detail: str = ""):
    """Fire the named injection point, if configured.

    Near-zero cost when no spec is active — the hot paths (engine push,
    batch iteration) call this unconditionally.
    """
    table = _active()
    if not table:
        return
    if point not in _POINT_SET:
        # only checked while chaos is configured: the no-spec hot path
        # above stays a dict-truthiness test
        raise ValueError(
            f"fault.inject called with undeclared point {point!r} "
            f"(declare it in fault.POINTS; known: {', '.join(POINTS)})")
    pt = table.get(point)
    if pt is None or not pt.should_fire():
        return
    # a fired injection annotates the active request trace (if any)
    # AND the always-on flight ring: chaos CI artifacts then SHOW the
    # fault and the recovery path on one timeline in BOTH systems
    # (docs/observability.md).  Lazy imports + only on fire, so the
    # no-spec and no-fire paths pay nothing.
    from . import trace as _trace
    _trace.add_event(f"fault.{point}", kind=pt.kind,
                     permanent=pt.permanent, fire=pt.fired,
                     detail=detail or None)
    from . import flightrec as _flightrec
    _flightrec.record(_flightrec.FAULT, f"fault.{point}",
                      severity="warn", kind=pt.kind,
                      permanent=pt.permanent, fire=pt.fired,
                      detail=detail or None)
    if pt.kind == "delay":
        time.sleep(pt.ms / 1000.0)
        return
    where = f"{point}" + (f" [{detail}]" if detail else "")
    if pt.permanent:
        raise PermanentFault(
            f"injected permanent fault at {where} (fire #{pt.fired})")
    raise TransientFault(
        f"injected transient fault at {where} (fire #{pt.fired})")


# ---------------------------------------------------------------------------
# shared retry helper
# ---------------------------------------------------------------------------

def retry(fn, max_attempts=None, backoff=0.05, max_backoff=2.0,
          jitter=0.5, retryable=(ConnectionError, TimeoutError),
          rng=None, on_retry=None):
    """Run ``fn()`` with exponential backoff on retryable failures.

    ``backoff * 2**k`` seconds between attempts (capped at
    ``max_backoff``), each scaled by a uniform ``[1-jitter, 1+jitter]``
    factor so a fleet of workers does not thunder-herd a recovering
    server.  :class:`PermanentFault` is never retried regardless of the
    ``retryable`` classes (it subclasses RuntimeError, but an explicit
    ``retryable=(RuntimeError,)`` must not swallow it either).  The
    last failure is re-raised once attempts are exhausted.

    ``on_retry(attempt, exc, sleep_s)`` runs before each sleep — the
    PSClient uses it to drop and re-establish its connection so a
    desynced stream is never reused.
    """
    attempts = int(max_attempts if max_attempts is not None
                   else get_env("MXNET_KVSTORE_RETRIES", 5, int))
    if attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {attempts}")
    rng = rng or random
    last = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except PermanentFault:
            raise
        except retryable as e:
            last = e
            if attempt == attempts:
                break
            sleep_s = min(backoff * (2 ** (attempt - 1)), max_backoff)
            if jitter:
                sleep_s *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
            if on_retry is not None:
                on_retry(attempt, e, sleep_s)
            time.sleep(sleep_s)
    raise last
